"""Fleet self-observability (ISSUE 10): job registry, event journal,
health model / readiness, self-scrape meta-monitoring, and the
runtimeinfo/CLI satellites.

Models ref: HealthRoute.scala / ClusterApiRoute.scala shard-status
admin; Prometheus /-/healthy + /-/ready + meta-monitoring."""
import json
import time
import urllib.request

import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.standalone import DatasetConfig, FiloServer
from filodb_tpu.utils.events import EventJournal, journal
from filodb_tpu.utils.health import (DEGRADED, FAILED, OK, SERVING,
                                     HealthEvaluator)
from filodb_tpu.utils.jobs import JobRegistry, jobs

START = 1_600_000_020_000
START_S = START // 1000


@pytest.fixture(autouse=True)
def _clean_registries():
    jobs.clear()
    yield
    jobs.clear()


# ------------------------------------------------------------ job registry

def test_job_tick_records_duration_and_streaks():
    reg = JobRegistry()
    h = reg.register("compact", interval_s=5.0, dataset="ds")
    with h.tick():
        h.set_progress("window 1/3")
        time.sleep(0.01)
    snap = h.snapshot()
    assert snap["runs"] == 1 and snap["errors"] == 0
    assert snap["consecutiveErrors"] == 0
    assert snap["lastDurationSeconds"] >= 0.01
    assert snap["progress"] == "window 1/3"
    assert snap["lastStartUnixSeconds"] > 0
    assert snap["lastEndUnixSeconds"] >= snap["lastStartUnixSeconds"]
    # an escaping exception marks the tick failed and re-raises
    with pytest.raises(RuntimeError):
        with h.tick():
            raise RuntimeError("boom")
    assert h.consecutive_errors == 1 and "boom" in h.last_error
    # streaks accumulate, success resets
    with pytest.raises(RuntimeError):
        with h.tick():
            raise RuntimeError("again")
    assert h.consecutive_errors == 2
    with h.tick():
        pass
    assert h.consecutive_errors == 0


def test_job_note_error_inside_tick_not_double_counted():
    """A loop that catches its own exceptions reports via note_error;
    the enclosing tick must count ONE run, failed."""
    reg = JobRegistry()
    h = reg.register("flush", dataset="ds")
    with h.tick():
        h.note_error("shard 3 flush failed")
    assert h.runs == 1
    assert h.errors == 1 and h.consecutive_errors == 1
    assert "shard 3" in h.last_error


def test_job_tick_skip_is_neutral():
    """An empty pass (every target in backoff) must not count as a
    success: a permanently broken critical job whose only failing
    target is backing off would otherwise oscillate its streak between
    0 and 1 and never flip /ready."""
    reg = JobRegistry()
    h = reg.register("skiptest", dataset="ds", critical=True)
    for _ in range(4):
        with h.tick():
            h.note_error("store down")     # attempted, failed
        with h.tick() as t:
            t.skip()                       # backoff pass: no work
    # skips neither reset the streak nor count as runs
    assert h.consecutive_errors == 4
    assert h.runs == 4
    # drop the exported streak gauge: the metrics registry is process-
    # wide, and a later self-scrape test would alert on this residue
    from filodb_tpu.utils.metrics import registry
    registry.gauge("job_consecutive_errors", job="skiptest",
                   dataset="ds").update(0)


def test_ruler_reload_unregisters_removed_group_jobs():
    """A removed group's job handle leaves the registry with it — a
    stale failing-group streak must not hold the health verdict
    degraded until process restart."""
    cfg = FilodbSettings()
    cfg.rules.enabled = True
    cfg.rules.groups = {"doomed": {"interval": 1, "rules": {
        "r": {"record": "x:y", "expr": "sum(rate(request_total[5m]))"}}}}
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     config=cfg)
    try:
        srv.ruler.evaluate_group("doomed", ts=time.time())
        h = jobs.get("ruler:doomed")
        assert h is not None
        h.note_error("induced streak")     # the group is failing
        ev = HealthEvaluator(phase=SERVING)
        assert ev.evaluate()["subsystems"]["jobs"]["status"] == DEGRADED
        srv.ruler.reload(groups=[])        # operator deletes the group
        assert jobs.get("ruler:doomed") is None
        assert ev.evaluate()["subsystems"]["jobs"]["status"] == OK
    finally:
        srv.shutdown()


def test_job_registry_bounded_and_idempotent():
    reg = JobRegistry()
    a = reg.register("x", dataset="d1")
    assert reg.register("x", dataset="d1") is a      # same handle back
    for i in range(reg.MAX_JOBS + 50):
        reg.register(f"j{i}")
    assert len(reg.snapshot()) <= reg.MAX_JOBS
    # overflow handles still work, they are just not retained
    extra = reg.register("overflow-job-xyz")
    with extra.tick():
        pass
    assert extra.runs == 1


def test_admin_jobs_route():
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)])
    try:
        h = jobs.register("probe", interval_s=1.0, dataset="prometheus")
        with h.tick():
            h.set_progress("probing")
        st, payload = srv.api.handle("GET", "/admin/jobs", {})
        assert st == 200
        by_name = {j["job"]: j for j in payload["data"]["jobs"]}
        assert by_name["probe"]["runs"] == 1
        assert by_name["probe"]["progress"] == "probing"
    finally:
        srv.shutdown()


# ------------------------------------------------------------ event journal

def test_journal_ring_bounded_with_monotonic_seqs():
    j = EventJournal(max_entries=64)
    for i in range(500):
        j.emit("tick", subsystem="t", i=i)
    evs = j.since(0)
    assert len(evs) == 64                      # bounded under a soak
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and seqs[-1] == 500
    # since_seq resumes exactly (exclusive), limit keeps the newest
    assert [e["seq"] for e in j.since(498)] == [499, 500]
    assert [e["seq"] for e in j.since(0, limit=3)] == [498, 499, 500]
    assert all(e["kind"] == "tick" for e in j.since(0, kind="tick"))
    assert j.since(0, kind="nope") == []


def test_journal_jsonl_sink(tmp_path):
    path = tmp_path / "events.jsonl"
    j = EventJournal(max_entries=8, path=str(path))
    j.emit("wal_segment_rotated", subsystem="wal", dataset="p",
           sealed_segments=2)
    j.emit("breaker_open", subsystem="peers", peer="10.0.0.1:9095")
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [ln["kind"] for ln in lines] == ["wal_segment_rotated",
                                           "breaker_open"]
    assert lines[0]["sealed_segments"] == 2
    assert lines[1]["seq"] == 2


def test_journal_emit_never_raises(tmp_path):
    j = EventJournal(max_entries=4, path=str(tmp_path / "nope" / "deep" /
                                             "x.jsonl"))
    # unwritable sink + unserializable field: emit still returns a seq
    class Weird:
        def __str__(self):
            return "weird"
    assert j.emit("k", field=Weird()) == 1


def test_subsystem_events_land_in_journal(tmp_path):
    """Wired emit sites: WAL rotation + prune and replay produce journal
    entries with their payload fields (the flight-recorder contract)."""
    from filodb_tpu.config import WalConfig
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.wal import WalManager
    seq0 = journal.next_seq
    cfg = WalConfig(enabled=True, segment_max_bytes=256, fsync=False)
    mgr = WalManager(str(tmp_path / "wal"), "prometheus", config=cfg)
    keys = None
    from filodb_tpu.ingest.generator import gauge_batch
    keys = gauge_batch(16, 1, start_ms=START).part_keys
    try:
        for b in range(6):
            ts = np.full((16, 1), START + b * 10_000, dtype=np.int64)
            vals = np.full((16, 1), float(b))
            mgr.append_grid(0, "gauge", list(keys), ts, {"value": vals})
    finally:
        mgr.close()
    # rotation events carry the sealed segment seqs
    rots = [e for e in journal.since(seq0 - 1)
            if e["kind"] == "wal_segment_rotated"]
    assert rots and rots[0]["dataset"] == "prometheus"
    # replay start/done pair with stats
    ms = TimeSeriesMemStore()
    mgr2 = WalManager(str(tmp_path / "wal"), "prometheus", config=cfg)
    try:
        mgr2.replay(ms)
    finally:
        mgr2.close()
    kinds = [e["kind"] for e in journal.since(seq0 - 1)]
    assert "wal_replay_started" in kinds and "wal_replay_done" in kinds
    done = [e for e in journal.since(seq0 - 1)
            if e["kind"] == "wal_replay_done"][-1]
    assert done["records"] == 6 and done["samples"] == 96


def test_admin_events_route_since_seq():
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)])
    try:
        seq = journal.emit("test_marker", subsystem="test", n=1)
        journal.emit("test_marker", subsystem="test", n=2)
        st, payload = srv.api.handle("GET", "/admin/events",
                                     {"since_seq": str(seq)})
        assert st == 200
        evs = payload["data"]["events"]
        assert all(e["seq"] > seq for e in evs)
        assert any(e.get("n") == 2 for e in evs)
        assert payload["data"]["nextSeq"] > seq
    finally:
        srv.shutdown()


# ------------------------------------------------------------- health model

def test_health_verdicts_fold_job_streaks():
    ev = HealthEvaluator(phase=SERVING)
    h = jobs.register("flush", dataset="p", critical=True)
    assert ev.evaluate()["status"] == OK
    h.note_error("disk full")
    tree = ev.evaluate()
    assert tree["status"] == DEGRADED
    assert tree["subsystems"]["jobs"]["status"] == DEGRADED
    ok, _ = ev.ready()
    assert ok                              # degraded still serves
    for _ in range(5):
        h.note_error("disk full")
    tree = ev.evaluate()
    assert tree["subsystems"]["jobs"]["status"] == FAILED
    ready, reason = ev.ready()
    assert not ready and "flush" in reason  # critical job failed -> 503
    h.note_ok()
    assert ev.ready()[0]


def test_health_peers_verdict_from_breakers():
    from filodb_tpu.parallel.breaker import breakers
    breakers.reset()
    breakers.configure(failure_threshold=1, open_base_s=30.0, jitter=0.0)
    try:
        ev = HealthEvaluator(phase=SERVING)
        br = breakers.get("10.0.0.9:9095")
        br.on_failure()                     # threshold 1 -> open
        tree = ev.evaluate()
        assert tree["subsystems"]["peers"]["status"] == DEGRADED
        assert tree["subsystems"]["peers"]["open"] == ["10.0.0.9:9095"]
        # open peers degrade but do NOT flip readiness (partials serve)
        assert ev.ready()[0]
    finally:
        breakers.configure()
        breakers.reset()


def test_ready_gated_on_phase():
    ev = HealthEvaluator(phase="booting")
    ok, reason = ev.ready()
    assert not ok and "booting" in reason
    ev.set_phase(SERVING)
    assert ev.ready()[0]
    # phase transitions land in the journal
    evs = [e for e in journal.since(0) if e["kind"] == "phase"]
    assert any(e["to"] == SERVING for e in evs)


# ------------------------------------------- readiness through a restart

def _rw_payload(n=8, k=4):
    from filodb_tpu.http import remotepb
    from filodb_tpu.utils import snappy
    series = []
    for i in range(n):
        labels = [("__name__", "restart_total"), ("_ws_", "demo"),
                  ("_ns_", "App-0"), ("inst", str(i))]
        samples = [(float(i + j), START + j * 10_000) for j in range(k)]
        series.append(remotepb.PromTimeSeries(labels, samples))
    return snappy.compress(remotepb.encode_write_request(series))


def test_ready_503_during_boot_replay_then_200_serving(tmp_path,
                                                       monkeypatch):
    """The acceptance restart test: a node restarting onto a WAL answers
    /ready with 503 WHILE the log replays (observed through the real
    route layer mid-replay) and flips to 200 once serving — with the
    whole sequence on the flight recorder."""
    from filodb_tpu.http.routes import PromHttpApi
    from filodb_tpu.wal import WalManager

    cfg = FilodbSettings()
    cfg.wal.enabled = True
    cfg.wal.dir = str(tmp_path / "wal")
    srv = FiloServer([DatasetConfig("prometheus", num_shards=2)],
                     config=cfg)
    try:
        st, _ = srv.api.handle("POST", "/api/v1/write", {}, _rw_payload())
        assert st == 204
    finally:
        srv.shutdown()

    # restart on the same WAL dir; probe /ready from INSIDE the replay
    # (the API is built before the boot replay runs, by design)
    box = {}
    orig_api_init = PromHttpApi.__init__

    def api_init(self, *a, **kw):
        orig_api_init(self, *a, **kw)
        box["api"] = self

    orig_replay = WalManager.replay

    def probed_replay(self, memstore, restart_points=None):
        api = box["api"]
        box["during_ready"] = api.handle("GET", "/ready", {})
        box["during_healthz"] = api.handle("GET", "/healthz", {})
        return orig_replay(self, memstore, restart_points)

    monkeypatch.setattr(PromHttpApi, "__init__", api_init)
    monkeypatch.setattr(WalManager, "replay", probed_replay)
    cfg2 = FilodbSettings()
    cfg2.wal.enabled = True
    cfg2.wal.dir = str(tmp_path / "wal")
    srv2 = FiloServer([DatasetConfig("prometheus", num_shards=2)],
                      config=cfg2, http_port=0)
    try:
        st, payload = box["during_ready"]
        assert st == 503 and payload["status"] == "unready"
        assert "replaying_wal" in payload["reason"]
        # liveness stayed 200 throughout (the Prometheus split)
        assert box["during_healthz"][0] == 200
        # not yet serving: constructed-but-unstarted stays unready
        assert srv2.api.handle("GET", "/ready", {})[0] == 503
        srv2.start()
        # ...and flips to 200 over the REAL socket once serving
        url = f"http://127.0.0.1:{srv2.http.port}/ready"
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200
        # the replayed data serves
        st, payload = srv2.api.handle(
            "GET", "/api/v1/query_range",
            {"query": "restart_total", "start": str(START_S),
             "end": str(START_S + 60), "step": "10"}, b"")
        assert st == 200 and len(payload["data"]["result"]) == 8
        # runtimeinfo reflects the WAL posture
        st, payload = srv2.api.handle("GET", "/api/v1/status/runtimeinfo",
                                      {})
        d = payload["data"]
        assert d["walEnabled"] is True and d["walReplayDone"] is True
        assert d["serverPhase"] == "serving"
        assert "startTime" in d and "serverTime" in d
        assert d["reloadConfigSuccess"] is True
    finally:
        srv2.shutdown()
    # the flight-recorder sequence of the restart
    kinds = [e["kind"] for e in journal.since(0)]
    assert "wal_replay_started" in kinds and "wal_replay_done" in kinds
    phases = [(e.get("frm"), e.get("to")) for e in journal.since(0)
              if e["kind"] == "phase"]
    assert ("booting", "replaying_wal") in phases
    assert any(to == "serving" for _f, to in phases)


# ------------------------------------------------ self-scrape meta-monitor

def _selfmon_server(interval_s=3600.0, rules_groups=None):
    cfg = FilodbSettings()
    cfg.selfmon.enabled = True
    cfg.selfmon.interval_s = interval_s     # manual scrape_once in tests
    if rules_groups is not None:
        cfg.rules.enabled = True
        cfg.rules.groups = rules_groups
    return FiloServer([DatasetConfig("prometheus", num_shards=2)],
                      config=cfg)


def test_selfmon_scrape_makes_metrics_promql_queryable():
    srv = _selfmon_server()
    try:
        from filodb_tpu.utils.metrics import registry
        # fresh names: the process-wide registry carries residue from
        # sibling tests, and counters only ever climb
        registry.counter("selfobs_probe",
                         dataset="prometheus").increment(7)
        registry.histogram("selfobs_probe_seconds",
                           dataset="prometheus").record(0.004)
        n = srv.selfmon.scrape_once()
        assert n > 0
        # query strictly AFTER the scrape timestamp: the instant API
        # floors to whole seconds and looks back, never forward
        now = int(time.time()) + 1
        # counter -> name_total, tagged with scrape identity
        st, p = srv.api.handle(
            "GET", "/api/v1/query",
            {"query": 'selfobs_probe_total{job="filodb",'
                      'dataset="prometheus"}', "time": str(now)})
        assert st == 200 and len(p["data"]["result"]) == 1
        row = p["data"]["result"][0]
        assert float(row["value"][1]) == 7.0
        assert row["metric"]["_ws_"] == "_self_"
        assert row["metric"]["instance"] == "local"
        # histogram -> _count/_sum/_bucket{le} (the rate(..._count[5m])
        # shape from the ISSUE)
        st, p = srv.api.handle(
            "GET", "/api/v1/query",
            {"query": "selfobs_probe_seconds_count", "time": str(now)})
        assert st == 200 and len(p["data"]["result"]) == 1
        assert float(p["data"]["result"][0]["value"][1]) == 1.0
        st, p = srv.api.handle(
            "GET", "/api/v1/query",
            {"query": 'selfobs_probe_seconds_bucket{le="+Inf"}',
             "time": str(now)})
        assert st == 200 and len(p["data"]["result"]) == 1
    finally:
        srv.shutdown()


def test_selfmon_label_collision_gets_exported_prefix():
    srv = _selfmon_server()
    try:
        h = jobs.register("victim", dataset="prometheus")
        with h.tick():
            pass
        srv.selfmon.scrape_once()
        now = int(time.time()) + 1
        st, p = srv.api.handle(
            "GET", "/api/v1/query",
            {"query": 'job_runs_total{job="filodb",'
                      'exported_job="victim"}', "time": str(now)})
        assert st == 200 and len(p["data"]["result"]) == 1
    finally:
        srv.shutdown()


def test_selfmon_alert_fires_through_frontend_end_to_end():
    """The acceptance e2e: an induced job error streak -> self-scraped
    `job_consecutive_errors` series -> ruler alert group evaluated
    through the ORDINARY frontend path -> firing at /api/v1/alerts."""
    # interval doubles as the per-eval deadline (ruler._planner_params);
    # 1 s sits at the edge of a cold-jit eval under a loaded suite, and
    # the deadline is not what this test verifies
    groups = {"self_monitoring": {
        "interval": 10,
        "rules": {"job_err": {
            "alert": "BackgroundJobFailing",
            "expr": 'max by (exported_job) '
                    '(job_consecutive_errors{job="filodb"}) > 2',
            "labels": {"severity": "page"},
        }}}}
    srv = _selfmon_server(rules_groups=groups)
    try:
        h = jobs.register("victim", dataset="prometheus")
        for _ in range(3):
            h.note_error("induced failure")
        srv.selfmon.scrape_once()
        # evaluate strictly AFTER the scrape timestamp (the eval ts
        # floors to whole seconds and the lookback is backward-only)
        ok = srv.ruler.evaluate_group("self_monitoring",
                                      ts=time.time() + 1)
        assert ok
        st, p = srv.api.handle("GET", "/api/v1/alerts", {})
        assert st == 200
        # filter to the induced instance: the process-wide metrics
        # registry may carry other tests' streak gauges
        mine = [a for a in p["data"]["alerts"]
                if a["labels"].get("exported_job") == "victim"]
        assert len(mine) == 1
        a = mine[0]
        assert a["labels"]["alertname"] == "BackgroundJobFailing"
        assert a["state"] == "firing"      # no `for:` -> fires at once
        # recovery clears it: streak resets, next scrape + eval resolve
        h.note_ok()
        srv.selfmon.scrape_once()
        assert srv.ruler.evaluate_group("self_monitoring",
                                        ts=time.time() + 2)
        st, p = srv.api.handle("GET", "/api/v1/alerts", {})
        assert not [a for a in p["data"]["alerts"]
                    if a["labels"].get("exported_job") == "victim"]
    finally:
        srv.shutdown()


def test_selfmon_tenant_accounted_but_scan_exempt():
    from filodb_tpu.utils.usage import INTERNAL_WORKSPACES, usage
    assert "_self_" in INTERNAL_WORKSPACES
    assert usage.admit("_self_", "selfmon", warn_limit=1,
                       fail_limit=1) is None


def test_suppressed_errors_counter_satellite():
    """log_error_once sites also increment
    suppressed_errors_total{site,class} on EVERY call (the log line is
    rate-limited; the counter is not)."""
    from filodb_tpu.utils.metrics import log_error_once, registry
    c = registry.counter("suppressed_errors",
                         **{"site": "test_site", "class": "ValueError"})
    v0 = c.value
    log_error_once("test_site", ValueError("x"))
    log_error_once("test_site", ValueError("y"))   # rate-limited log,
    assert c.value == v0 + 2                       # counted twice
    assert 'suppressed_errors_total{class="ValueError",site="test_site"}' \
        in registry.expose_prometheus()


# ------------------------------------------------------------ CLI satellite

@pytest.fixture(scope="module")
def live_server():
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     http_port=0)
    srv.start()
    yield srv
    srv.shutdown()


def test_cli_health_jobs_events(live_server, capsys):
    from filodb_tpu.cli import main
    host = f"127.0.0.1:{live_server.http.port}"
    h = jobs.register("cli-probe", dataset="prometheus")
    with h.tick():
        h.set_progress("cli visibility")
    seq = journal.emit("cli_marker", subsystem="test", n=41)
    journal.emit("cli_marker", subsystem="test", n=42)

    assert main(["health", "--host", host]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["data"]["status"] in ("ok", "degraded")
    assert "jobs" in out["data"]["subsystems"]

    assert main(["health", "--host", host, "--ready"]) == 0
    assert json.loads(capsys.readouterr().out)["status"] == "ready"

    assert main(["jobs", "--host", host]) == 0
    out = capsys.readouterr().out
    assert "cli-probe" in out and "cli visibility" in out

    assert main(["events", "--host", host, "--since-seq", str(seq)]) == 0
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines()]
    assert all(ev["seq"] > seq for ev in lines)
    assert any(ev.get("n") == 42 for ev in lines)

    # --kind filters
    assert main(["events", "--host", host, "--kind", "cli_marker"]) == 0
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines()]
    assert lines and all(ev["kind"] == "cli_marker" for ev in lines)


def test_http_healthz_ready_over_socket(live_server):
    port = live_server.http.port
    for path, want in (("/healthz", 200), ("/ready", 200),
                       ("/__health", 200)):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            assert r.status == want
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/v1/status/health",
            timeout=30) as r:
        doc = json.loads(r.read())
    assert doc["data"]["phase"] == "serving"
    assert set(doc["data"]["subsystems"]) >= {"jobs", "peers", "wal",
                                              "shards", "mirror"}
