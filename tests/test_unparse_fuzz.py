"""Parse -> unparse -> parse round-trip fuzz over a compositional PromQL
grammar.

planutils.unparse is the REMOTE-DISPATCH WIRE CONTRACT: the HA,
multi-partition, and long-time-range planners ship plans to peers as
PromQL text (query/planners.py PromQlRemoteExec), so any plan shape
whose unparse doesn't re-parse to the same plan silently changes query
semantics across nodes — exactly the absent_over_time label-loss bug
review r4 caught.  This fuzz pins the contract over ~400 generated
expressions (fixed seed: failures are reproducible).
"""
import random

import pytest

from filodb_tpu.promql.parser import (TimeStepParams,
                                      query_range_to_logical_plan)
from filodb_tpu.query import planutils as pu

TSP = TimeStepParams(10_000, 60, 12_000)

METRICS = ["http_requests", "mem_used", "disk_io"]
LABELS = [('job', 'api'), ('dc', 'east'), ('tier', 'web')]
RANGE_FNS = ["rate", "increase", "delta", "irate", "idelta", "resets",
             "changes", "deriv", "sum_over_time", "avg_over_time",
             "min_over_time", "max_over_time", "count_over_time",
             "stddev_over_time", "stdvar_over_time", "last_over_time",
             "present_over_time", "absent_over_time"]
INSTANT_FNS = ["abs", "ceil", "floor", "exp", "ln", "sqrt", "sgn",
               "sin", "cos", "log2", "log10"]
AGGS = ["sum", "min", "max", "avg", "count", "stddev", "group"]
BIN_OPS = ["+", "-", "*", "/", "%", "and", "or", "unless",
           "==", "!=", ">", "<", ">=", "<="]


def _selector(rng):
    m = rng.choice(METRICS)
    n = rng.randrange(0, 3)
    if n == 0:
        return m
    pairs = rng.sample(LABELS, n)
    ops = [rng.choice(['=', '!=', '=~']) for _ in pairs]
    body = ",".join(f'{k}{op}"{v}"' for (k, v), op in zip(pairs, ops))
    return f'{m}{{{body}}}'


def _offset(rng):
    return rng.choice(["", "", " offset 5m", " offset 1h"])


def _at(rng):
    return rng.choice(["", "", "", " @ 11", " @ 10.5"])


def _vector(rng, depth):
    r = rng.random()
    if depth <= 0 or r < 0.25:
        return f"{_selector(rng)}{_offset(rng)}"
    if r < 0.55:
        fn = rng.choice(RANGE_FNS)
        win = rng.choice(["5m", "10m", "1h"])
        if rng.random() < 0.2:
            # subquery form (optionally @-pinned)
            return (f"{fn}(({_vector(rng, depth - 1)})"
                    f"[{win}:{rng.choice(['1m', '2m'])}]{_at(rng)})")
        return f"{fn}({_selector(rng)}[{win}]{_offset(rng)}{_at(rng)})"
    if r < 0.7:
        return f"{rng.choice(INSTANT_FNS)}({_vector(rng, depth - 1)})"
    if r < 0.88:
        agg = rng.choice(AGGS)
        clause = rng.choice(["", " by (job)", " by (job,dc)",
                             " without (tier)"])
        return f"{agg}({_vector(rng, depth - 1)}){clause}"
    lhs = _vector(rng, depth - 1)
    rhs = (str(rng.randrange(1, 100)) if rng.random() < 0.4
           else _vector(rng, depth - 1))
    op = rng.choice(BIN_OPS)
    if op in ("and", "or", "unless") and not rhs[0].isalpha():
        rhs = _selector(rng)                    # set ops need vectors
    b = ("bool " if op in ("==", "!=", ">", "<", ">=", "<=")
         and rng.random() < 0.5 and rhs[0].isdigit() else "")
    return f"({lhs}) {op} {b}({rhs})"


@pytest.mark.parametrize("seed", range(8))
def test_unparse_roundtrip_fuzz(seed):
    rng = random.Random(seed)
    checked = 0
    for _ in range(50):
        expr = _vector(rng, 3)
        try:
            plan = query_range_to_logical_plan(expr, TSP)
        except Exception:
            continue                  # generator produced invalid PromQL
        text = pu.unparse(plan)
        try:
            plan2 = query_range_to_logical_plan(text, TSP)
        except Exception as e:
            raise AssertionError(
                f"unparse produced unparseable text\n  expr: {expr}\n"
                f"  unparse: {text}\n  error: {e}") from None
        assert plan2 == plan, (
            f"round-trip changed the plan\n  expr:    {expr}\n"
            f"  unparse: {text}\n  plan:  {plan}\n  plan2: {plan2}")
        checked += 1
    assert checked >= 30, f"only {checked} valid expressions generated"
