"""Kafka ingestion-transport integration (ref: kafka/src/it/
SourceSinkSuite.scala — produce, consume via the source, verify, resume).

No broker runs in CI, so these tests run the full contract against a
DURABLE broker fake: per-(topic, partition) append logs on disk, offsets
assigned at append, consumers positioned by offset — the exact semantics
KafkaIngestionStream depends on.  Everything downstream of the consumer is
the real pipeline: RecordBatch wire frames, IngestionStream, memstore
ingest with group-watermark checkpoints, crash + resume from the
checkpointed offset.
"""
import os

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.ingest.kafka import KafkaIngestionStream
from filodb_tpu.ingest.stream import create_stream
from filodb_tpu.query.engine import QueryEngine

START = 1_600_000_000_000


class FileBackedBroker:
    """Append-log-per-partition broker fake with Kafka offset semantics."""

    def __init__(self, root):
        self.root = str(root)

    def _path(self, topic, partition):
        return os.path.join(self.root, f"{topic}-{partition}.log")

    def produce(self, topic: str, partition: int, value: bytes) -> int:
        """Append; returns the assigned offset."""
        path = self._path(topic, partition)
        offset = len(self._read_all(topic, partition))
        with open(path, "ab") as f:
            f.write(len(value).to_bytes(4, "big") + value)
        return offset

    def _read_all(self, topic, partition):
        path = self._path(topic, partition)
        out = []
        if not os.path.exists(path):
            return out
        with open(path, "rb") as f:
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return out
                out.append(f.read(int.from_bytes(hdr, "big")))

    class _Msg:
        def __init__(self, offset, value):
            self.offset, self.value = offset, value

    def consumer_factory(self):
        broker = self

        def factory(topic, partition, from_offset):
            msgs = [FileBackedBroker._Msg(i, v) for i, v in
                    enumerate(broker._read_all(topic, partition))
                    if i > from_offset]
            return iter(msgs)
        return factory


def _produce_slices(broker, topic, partition, num_slices=10, series=30,
                    samples_per=12):
    """Chop one canonical batch into time slices and produce each as one
    Kafka message (a RecordContainer analogue)."""
    T = num_slices * samples_per
    full = counter_batch(series, T, start_ms=START)
    for i in range(num_slices):
        lo = START + i * samples_per * 10_000
        hi = lo + samples_per * 10_000
        k = (full.timestamps >= lo) & (full.timestamps < hi)
        sub = RecordBatch(full.schema, full.part_keys, full.part_idx[k],
                          full.timestamps[k],
                          {kk: v[k] for kk, v in full.columns.items()},
                          full.bucket_les)
        broker.produce(topic, partition, sub.to_bytes())
    return full


def test_source_consumes_from_beginning(tmp_path):
    broker = FileBackedBroker(tmp_path)
    full = _produce_slices(broker, "timeseries", 0)
    stream = KafkaIngestionStream(
        "timeseries", shard=0, consumer_factory=broker.consumer_factory())
    got = list(stream.batches(from_offset=-1))
    stream.teardown()
    assert [off for _, off in got] == list(range(10))
    total = sum(b.num_records for b, _ in got)
    assert total == full.num_records
    # frames round-trip exactly (slicing reorders rows; contents match)
    np.testing.assert_array_equal(
        np.sort(np.concatenate([b.timestamps for b, _ in got])),
        np.sort(full.timestamps))
    np.testing.assert_array_equal(
        np.sort(np.concatenate([b.columns["count"] for b, _ in got])),
        np.sort(full.columns["count"]))


def test_source_resumes_after_offset(tmp_path):
    broker = FileBackedBroker(tmp_path)
    _produce_slices(broker, "timeseries", 0)
    stream = KafkaIngestionStream(
        "timeseries", shard=0, consumer_factory=broker.consumer_factory())
    got = list(stream.batches(from_offset=6))
    assert [off for _, off in got] == [7, 8, 9]


def test_registry_builds_kafka_stream(tmp_path):
    broker = FileBackedBroker(tmp_path)
    _produce_slices(broker, "timeseries", 0, num_slices=2)
    stream = create_stream("kafka", topic="timeseries", shard=0,
                           consumer_factory=broker.consumer_factory())
    assert len(list(stream.batches())) == 2


def test_end_to_end_ingest_crash_resume(tmp_path):
    """The SourceSinkSuite shape: consume into a shard with interleaved
    flushes, crash, restart from the checkpointed group watermarks, and
    end with byte-identical query results vs an unfailed run."""
    broker = FileBackedBroker(tmp_path / "broker")
    os.makedirs(tmp_path / "broker")
    full = _produce_slices(broker, "timeseries", 0)
    end_s = START // 1000 + 1190

    def query(ms):
        eng = QueryEngine("prometheus", ms)
        res = eng.query_range('sum by (_ns_)(rate(request_total[5m]))',
                              START // 1000 + 600, 60, end_s)
        assert res.error is None, res.error
        return {str(k): np.asarray(v) for k, _, v in res.series()}

    # run 1: consume messages 0..5 with flushes, then "crash"
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    ms.setup("prometheus", 0)
    stream = KafkaIngestionStream(
        "timeseries", shard=0, consumer_factory=broker.consumer_factory())

    def first_six():
        for batch, off in stream.batches(-1):
            if off >= 6:
                return
            yield batch, off
    ms.ingest_stream("prometheus", 0, first_six(), flush_every=2)
    ms.get_shard("prometheus", 0).flush_all_groups()

    # run 2 (restart): recover index, read the checkpoint watermark, resume
    # the stream from it — replay filtering drops already-persisted rows
    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh2 = ms2.setup("prometheus", 0)
    sh2.recover_index()
    checkpoints = meta.read_checkpoints("prometheus", 0)
    resume_from = min(checkpoints.values()) if checkpoints else -1
    assert resume_from >= 0, "flushes never checkpointed"
    stream2 = KafkaIngestionStream(
        "timeseries", shard=0, consumer_factory=broker.consumer_factory())
    sh2.recover_stream(
        (b, off) for b, off in stream2.batches(resume_from))

    # truth: one uninterrupted consume into a fresh store
    truth = TimeSeriesMemStore()
    truth.setup("prometheus", 0)
    stream3 = KafkaIngestionStream(
        "timeseries", shard=0, consumer_factory=broker.consumer_factory())
    truth.ingest_stream("prometheus", 0, stream3.batches(-1))

    got, want = query(ms2), query(truth)
    assert set(got) == set(want) and len(want) == 10
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-9,
                                   equal_nan=True)
    assert sh2.stats.rows_dropped == 0
