"""Cardinality tracking + quota tests (models ref: core/src/test/.../
ratelimit/CardinalityTrackerSpec, RocksDbCardinalityStoreSpec)."""
import json
import urllib.request

import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.ratelimit import (CardinalityRecord,
                                       CardinalityTracker,
                                       InMemoryCardinalityStore,
                                       QuotaReachedException, QuotaSource,
                                       SqliteCardinalityStore)
from filodb_tpu.ingest.generator import gauge_batch

START = 1_600_000_020_000


def _track_n(tracker, ws, ns, metric, n):
    for i in range(n):
        tracker.series_created((ws, ns, f"{metric}{i}"))


def test_counts_at_every_depth():
    t = CardinalityTracker()
    _track_n(t, "demo", "App-1", "m", 5)
    _track_n(t, "demo", "App-2", "m", 3)
    assert t.cardinality(()).ts_count == 8
    assert t.cardinality(("demo",)).ts_count == 8
    assert t.cardinality(("demo", "App-1")).ts_count == 5
    assert t.cardinality(("demo", "App-2")).ts_count == 3
    assert t.cardinality(("demo",)).children_count == 2
    top = t.top_k(("demo",), 1)
    assert top[0].prefix == ("demo", "App-1") and top[0].ts_count == 5


def test_quota_enforced_at_prefix():
    qs = QuotaSource(default_quota=1_000_000)
    qs.set_quota(("demo", "App-1"), 3)
    t = CardinalityTracker(quota_source=qs)
    _track_n(t, "demo", "App-1", "m", 3)
    with pytest.raises(QuotaReachedException) as ei:
        t.series_created(("demo", "App-1", "m99"))
    assert ei.value.prefix == ("demo", "App-1")
    # sibling namespace unaffected
    t.series_created(("demo", "App-2", "m0"))
    # failed creation did not corrupt parent counts
    assert t.cardinality(("demo",)).ts_count == 4


def test_counts_decrement_on_stop_and_churn_is_quota_neutral():
    t = CardinalityTracker()
    _track_n(t, "demo", "App-1", "m", 4)
    t.series_stopped(("demo", "App-1", "m0"))
    rec = t.cardinality(("demo", "App-1"))
    # eviction releases quota: both counts drop, re-ingest re-counts
    assert rec.ts_count == 3 and rec.active_ts_count == 3
    t.series_created(("demo", "App-1", "m0"))
    assert t.cardinality(("demo", "App-1")).ts_count == 4


def test_children_count_stable_under_churn():
    t = CardinalityTracker()
    for _ in range(5):
        for i in range(3):
            t.series_created(("demo", f"App-{i}", "m"))
        assert t.cardinality(("demo",)).children_count == 3
        assert t.cardinality(()).children_count == 1
        for i in range(3):
            t.series_stopped(("demo", f"App-{i}", "m"))
        assert t.cardinality(("demo",)).children_count == 0


def test_evict_reingest_does_not_exhaust_quota():
    qs = QuotaSource(default_quota=1_000_000)
    qs.set_quota(("demo",), 3)
    t = CardinalityTracker(quota_source=qs)
    for round_ in range(5):           # churn the same 3 series repeatedly
        for i in range(3):
            t.series_created(("demo", f"App-{i}", "m"))
        for i in range(3):
            t.series_stopped(("demo", f"App-{i}", "m"))
    assert t.cardinality(("demo",)).ts_count == 0


def test_sqlite_store_roundtrip(tmp_path):
    store = SqliteCardinalityStore(str(tmp_path / "card.db"))
    t = CardinalityTracker(store=store)
    _track_n(t, "demo", "App-1", "m", 5)
    store.close()
    store2 = SqliteCardinalityStore(str(tmp_path / "card.db"))
    t2 = CardinalityTracker(store=store2)
    assert t2.cardinality(("demo", "App-1")).ts_count == 5
    assert t2.top_k(("demo",), 5)[0].ts_count == 5
    store2.close()


def test_shard_drops_series_over_quota():
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    qs = QuotaSource(default_quota=1_000_000)
    qs.set_quota((), 6)               # only 6 series fit the whole shard
    shard.cardinality_tracker = CardinalityTracker(quota_source=qs)
    batch = gauge_batch(10, 100, start_ms=START)
    n = shard.ingest(batch)
    assert shard.num_partitions == 6
    assert shard.stats.quota_dropped == 4
    assert n == 6 * 100
    assert shard.stats.rows_dropped == 4 * 100


def test_http_cardinality_endpoint():
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)], http_port=0)
    srv.memstore.get_shard("prometheus", 0).ingest(
        gauge_batch(20, 10, start_ms=START))
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.http.port}/promql/prometheus/api/v1/"
               f"metering/cardinality?prefix=&k=5")
        with urllib.request.urlopen(url, timeout=30) as r:
            payload = json.loads(r.read())
        assert payload["status"] == "success"
        assert payload["data"], "no cardinality rows"
        assert payload["data"][0]["prefix"] == ["demo"]
        assert payload["data"][0]["tsCount"] == 20
    finally:
        srv.shutdown()


def test_cli_topkcard(tmp_path, capsys):
    from filodb_tpu.cli import main, _open_local
    data_dir = str(tmp_path / "data")
    main(["init", "--data-dir", data_dir])
    ms, _, _ = _open_local(data_dir, "prometheus", 1)
    sh = ms.get_shard("prometheus", 0)
    sh.ingest(gauge_batch(12, 10, start_ms=START))
    sh.flush_all_groups()
    capsys.readouterr()
    rc = main(["topkcard", "--data-dir", data_dir, "--prefix", "demo"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "App-" in out


def test_sqlite_store_batched_writes_and_flush(tmp_path):
    """Writes buffer (no per-write commit) and persist on flush()/close();
    reads and child scans see buffered records (VERDICT r2: RocksDB-style
    memtable batching instead of commit-per-write)."""
    path = str(tmp_path / "card.db")
    store = SqliteCardinalityStore(path, flush_every=1000)
    for i in range(50):
        store.write(CardinalityRecord(("demo", f"App-{i}"), ts_count=i + 1))
    # buffered, not yet committed: a second connection sees nothing
    other = SqliteCardinalityStore(path)
    assert other.read(("demo", "App-0")) is None
    # but THIS store's reads and scans see the buffer
    assert store.read(("demo", "App-7")).ts_count == 8
    assert len(store.scan_children(("demo",))) == 50   # scan flushes
    other2 = SqliteCardinalityStore(path)
    assert other2.read(("demo", "App-0")).ts_count == 1
    other.close()
    other2.close()
    store.close()


def test_sqlite_store_crash_recovery(tmp_path):
    """Flushed records survive an abrupt crash (connection never closed);
    the WAL replays on reopen."""
    path = str(tmp_path / "card.db")
    store = SqliteCardinalityStore(path, flush_every=10)
    for i in range(25):                 # crosses two auto-flush boundaries
        store.write(CardinalityRecord(("ws", f"ns-{i}"), ts_count=i))
    store.flush()
    # simulate crash: drop every reference without close()
    del store._conn
    del store
    back = SqliteCardinalityStore(path)
    assert len(back.scan_children(("ws",))) == 25
    assert back.read(("ws", "ns-24")).ts_count == 24
    back.close()


def test_tracker_flush_rides_shard_flush(tmp_path):
    """The shard flush cycle persists buffered cardinality updates."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import gauge_batch

    path = str(tmp_path / "card.db")
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    store = SqliteCardinalityStore(path, flush_every=1 << 20)  # never auto
    sh.cardinality_tracker = CardinalityTracker(store=store)
    sh.ingest(gauge_batch(12, 30))
    assert store._dirty                 # buffered, not yet persisted
    sh.flush_all_groups()
    assert not store._dirty
    fresh = SqliteCardinalityStore(path)
    assert fresh.read(("demo",)) is not None
    fresh.close()
    store.close()
