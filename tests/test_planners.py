"""Planner-hierarchy tests (models ref: coordinator/src/test/.../queryplanner/
LongTimeRangePlannerSpec, HighAvailabilityPlannerSpec,
MultiPartitionPlannerSpec, ShardKeyRegexPlannerSpec, LogicalPlanParserSpec)."""
import numpy as np
import pytest

from filodb_tpu.core.index import Equals, EqualsRegex
from filodb_tpu.query import logical as lp
from filodb_tpu.query import planutils as pu
from filodb_tpu.query.exec import ExecPlan, StitchRvsExec
from filodb_tpu.query.planner import QueryPlanner
from filodb_tpu.query.planners import (FailureProvider, FailureTimeRange,
                                       HighAvailabilityPlanner, LocalRoute,
                                       LongTimeRangePlanner,
                                       MultiPartitionPlanner,
                                       MultiPartitionReduceAggregateExec,
                                       PartitionAssignment,
                                       PartitionLocationProvider,
                                       PromQlRemoteExec, RemoteRoute,
                                       ShardKeyRegexPlanner,
                                       SinglePartitionPlanner,
                                       _matrix_json_to_block, plan_routes)
from filodb_tpu.query.planutils import TimeRange
from filodb_tpu.query.rangevector import (QueryContext, QueryStats,
                                          RangeVectorKey, ResultBlock)
from filodb_tpu.promql.parser import (TimeStepParams,
                                      query_range_to_logical_plan)

START_S = 1_600_000_000
T = TimeStepParams(START_S, 60, START_S + 3600)


def _plan(q, params=T):
    return query_range_to_logical_plan(q, params)


class _Dummy(ExecPlan):
    def __init__(self, tag, plan=None):
        super().__init__(QueryContext())
        self.tag = tag
        self.plan = plan

    def _do_execute(self, source):
        return None, QueryStats()


class _RecordingPlanner(QueryPlanner):
    def __init__(self, tag):
        self.tag = tag
        self.materialized = []

    def materialize(self, plan, ctx):
        self.materialized.append(plan)
        return _Dummy(self.tag, plan)


# ------------------------------------------------------------- unparse


@pytest.mark.parametrize("q", [
    'sum(rate(http_requests_total{job="api"}[5m]))',
    'sum by (job,instance)(rate(foo{_ws_="demo",_ns_="app"}[1m]))',
    'histogram_quantile(0.9,sum by (le)(rate(req_bucket{job="a"}[1m])))',
    'foo{job="x"}',
    'foo{job!="x",mode=~"user|sys"}',
    '(foo{a="1"} + bar{b="2"})',
    '(foo{a="1"} * on (host) group_left () bar{b="2"})',
    '(foo{a="1"} > bool 10)',
    'topk(5,foo{job="j"})',
    'quantile(0.5,foo{job="j"})',
    'abs(foo{job="j"})',
    'clamp_max(foo{job="j"},100)',
    'label_replace(foo{job="j"},"dst","$1","src","(.*)")',
    'sort_desc(foo{job="j"})',
    'avg_over_time(foo{job="j"}[10m])',
    'min_over_time((rate(foo{job="j"}[5m]))[30m:1m])',
])
def test_unparse_round_trip(q):
    p1 = _plan(q)
    s = pu.unparse(p1)
    p2 = _plan(s)
    assert p1 == p2, f"{q!r} -> {s!r} did not round-trip"


def test_unparse_offset_and_column():
    p = _plan('rate(foo::count{job="x"}[5m] offset 10m)')
    s = pu.unparse(p)
    assert "offset 10m" in s and "::count" in s
    assert _plan(s) == p


# -------------------------------------------------- time-range utilities


def test_copy_with_time_range_rewrites_selector():
    p = _plan('sum(rate(foo{job="x"}[5m]))')
    tr = TimeRange(START_S * 1000 + 600_000, START_S * 1000 + 1_200_000)
    p2 = pu.copy_with_time_range(p, tr)
    assert p2.start_ms == tr.start_ms and p2.end_ms == tr.end_ms
    inner = p2.vectors.series
    # raw fetch reaches back one window before the new start
    assert inner.range_selector.from_ms == tr.start_ms - 300_000
    assert inner.range_selector.to_ms == tr.end_ms


def test_split_plans_on_grid():
    p = _plan('foo{job="x"}', TimeStepParams(START_S, 60, START_S + 86_400))
    parts = pu.split_plans(p, 6 * 3600 * 1000)
    assert len(parts) == 4
    assert parts[0].start_ms == p.start_ms
    assert parts[-1].end_ms == p.end_ms
    for a, b in zip(parts, parts[1:]):
        assert b.start_ms == a.end_ms + p.step_ms
        assert (a.end_ms - a.start_ms) % p.step_ms == 0


def test_get_lookback_window():
    assert pu.get_lookback_ms(_plan('rate(foo[5m])'), 300_000) == 300_000
    assert pu.get_lookback_ms(_plan('sum(rate(foo[15m]))'), 300_000) == 900_000
    assert pu.get_lookback_ms(_plan('foo'), 300_000) == 300_000


# ------------------------------------------------------ LongTimeRange


def _ltr(earliest_raw_ms, latest_ds_ms):
    raw, ds = _RecordingPlanner("raw"), _RecordingPlanner("downsample")
    return LongTimeRangePlanner(raw, ds, lambda: earliest_raw_ms,
                                lambda: latest_ds_ms), raw, ds


def test_ltr_all_raw():
    start_ms = START_S * 1000
    planner, raw, ds = _ltr(start_ms - 7 * 86_400_000, start_ms - 6 * 3600_000)
    out = planner.materialize(_plan('rate(foo[5m])'), QueryContext())
    assert isinstance(out, _Dummy) and out.tag == "raw"
    assert not ds.materialized


def test_ltr_all_downsample():
    start_ms = START_S * 1000
    planner, raw, ds = _ltr(start_ms + 2 * 3600_000 + 600_000, start_ms + 4e7)
    out = planner.materialize(_plan('rate(foo[5m])'), QueryContext())
    assert isinstance(out, _Dummy) and out.tag == "downsample"
    assert not raw.materialized


def test_ltr_straddle_splits_and_stitches():
    start_ms = START_S * 1000
    earliest_raw = start_ms + 20 * 60_000          # raw starts 20m into query
    planner, raw, ds = _ltr(earliest_raw, start_ms + 86_400_000)
    p = _plan('rate(foo[5m])')
    out = planner.materialize(p, QueryContext())
    assert isinstance(out, StitchRvsExec)
    ds_plan, raw_plan = ds.materialized[0], raw.materialized[0]
    # raw part starts at the first grid instant whose 5m window is in raw
    assert raw_plan.start_ms >= earliest_raw + 300_000
    assert (raw_plan.start_ms - p.start_ms) % p.step_ms == 0
    assert raw_plan.end_ms == p.end_ms
    assert ds_plan.start_ms == p.start_ms
    assert ds_plan.end_ms == raw_plan.start_ms - p.step_ms


# --------------------------------------------------------- HA routing


def test_plan_routes_no_failures():
    assert plan_routes(0, 60, 600, [], 300) == [LocalRoute()]


def test_plan_routes_mid_failure():
    start, step, end = 1_000_000, 60_000, 4_000_000
    fail = TimeRange(2_000_000, 2_100_000)
    routes = plan_routes(start, step, end, [fail], 300_000)
    assert isinstance(routes[0], LocalRoute)
    assert isinstance(routes[1], RemoteRoute)
    assert isinstance(routes[2], LocalRoute)
    # local instants never have a window overlapping the failure
    assert routes[0].time_range.end_ms < fail.start_ms
    assert routes[2].time_range.start_ms - 300_000 >= fail.end_ms
    # grid continuity
    assert routes[1].time_range.start_ms == \
        routes[0].time_range.end_ms + step
    assert routes[2].time_range.start_ms == \
        routes[1].time_range.end_ms + step
    assert routes[2].time_range.end_ms == end


class _FP(FailureProvider):
    def __init__(self, failures):
        self.failures = failures

    def get_failures(self, dataset, tr):
        return [f for f in self.failures
                if f.time_range.end_ms >= tr.start_ms
                and f.time_range.start_ms <= tr.end_ms]


def test_ha_planner_no_failure_goes_local():
    local = _RecordingPlanner("local")
    ha = HighAvailabilityPlanner("ds", local, _FP([]), "http://remote/api")
    out = ha.materialize(_plan('rate(foo[5m])'), QueryContext())
    assert isinstance(out, _Dummy) and out.tag == "local"


def test_ha_planner_failure_routes_remote():
    local = _RecordingPlanner("local")
    start_ms = START_S * 1000
    fail = FailureTimeRange("local", TimeRange(start_ms + 1_200_000,
                                               start_ms + 1_500_000))
    ha = HighAvailabilityPlanner("ds", local, _FP([fail]), "http://remote/api")
    p = _plan('sum(rate(foo{job="x"}[5m]))')
    out = ha.materialize(p, QueryContext())
    assert isinstance(out, StitchRvsExec)
    remotes = [c for c in out.children if isinstance(c, PromQlRemoteExec)]
    assert len(remotes) == 1
    assert remotes[0].endpoint == "http://remote/api"
    # the remote query is the same PromQL re-rendered
    assert "rate" in remotes[0].promql and 'job="x"' in remotes[0].promql
    # remote covers the failure window
    assert remotes[0].start_ms <= fail.time_range.end_ms
    assert remotes[0].end_ms >= fail.time_range.start_ms


def test_remote_failure_is_ignored():
    local = _RecordingPlanner("local")
    start_ms = START_S * 1000
    fail = FailureTimeRange("remote", TimeRange(start_ms, start_ms + 600_000),
                            is_remote=True)
    ha = HighAvailabilityPlanner("ds", local, _FP([fail]), "http://remote/api")
    out = ha.materialize(_plan('rate(foo[5m])'), QueryContext())
    assert isinstance(out, _Dummy) and out.tag == "local"


# ----------------------------------------------------- multi-partition


class _Provider(PartitionLocationProvider):
    def __init__(self, assignments):
        self.assignments = assignments

    def get_partitions(self, filters, tr):
        return self.assignments


def test_multi_partition_all_local():
    local = _RecordingPlanner("local")
    start_ms, end_ms = START_S * 1000, (START_S + 3600) * 1000
    prov = _Provider([PartitionAssignment("local", "",
                                          TimeRange(0, end_ms * 2))])
    mp = MultiPartitionPlanner(prov, "local", local)
    out = mp.materialize(_plan('rate(foo[5m])'), QueryContext())
    assert isinstance(out, _Dummy) and out.tag == "local"


def test_multi_partition_splits_by_time():
    local = _RecordingPlanner("local")
    start_ms = START_S * 1000
    mid = start_ms + 1800_000
    prov = _Provider([
        PartitionAssignment("remote-p", "http://p2/api",
                            TimeRange(0, mid - 1)),
        PartitionAssignment("local", "", TimeRange(mid, start_ms + 10**9)),
    ])
    mp = MultiPartitionPlanner(prov, "local", local)
    p = _plan('rate(foo{job="x"}[5m])')
    out = mp.materialize(p, QueryContext())
    assert isinstance(out, StitchRvsExec)
    remote = [c for c in out.children if isinstance(c, PromQlRemoteExec)][0]
    local_child = [c for c in out.children if isinstance(c, _Dummy)][0]
    assert remote.start_ms == p.start_ms
    assert local_child.plan.end_ms == p.end_ms
    # no overlap, grid-aligned
    assert (local_child.plan.start_ms - p.start_ms) % p.step_ms == 0
    assert local_child.plan.start_ms > remote.end_ms


def test_matrix_json_to_block():
    payload = {"status": "success", "data": {"resultType": "matrix", "result": [
        {"metric": {"job": "x"}, "values": [[START_S, "1.5"],
                                            [START_S + 60, "2.5"]]},
        {"metric": {"job": "y"}, "values": [[START_S + 60, "7"]]},
    ]}}
    b = _matrix_json_to_block(payload)
    assert b.num_series == 2
    assert list(b.wends) == [START_S * 1000, (START_S + 60) * 1000]
    assert b.values[0][0] == 1.5 and b.values[1][1] == 7.0
    assert np.isnan(b.values[1][0])


def test_remote_exec_with_fake_transport():
    calls = []

    def transport(endpoint, params):
        calls.append((endpoint, params))
        return {"data": {"result": [{"metric": {"a": "b"},
                                     "values": [[START_S, "4"]]}]}}

    e = PromQlRemoteExec(QueryContext(), "http://r/api", "up", START_S * 1000,
                         60_000, (START_S + 600) * 1000, transport=transport)
    res = e.execute(None)
    assert res.error is None
    assert res.num_series == 1
    assert calls[0][1]["query"] == "up"
    assert calls[0][1]["step"] == 60


# ---------------------------------------------------- single partition


def test_single_partition_selects_by_metric():
    a, b = _RecordingPlanner("a"), _RecordingPlanner("b")
    sp = SinglePartitionPlanner(
        {"a": a, "b": b},
        planner_selector=lambda m: "b" if m.startswith("agg_") else "a")
    out1 = sp.materialize(_plan('rate(foo{job="x"}[5m])'), QueryContext())
    out2 = sp.materialize(_plan('rate(agg_foo{job="x"}[5m])'), QueryContext())
    assert out1.tag == "a" and out2.tag == "b"


# --------------------------------------------------- shard-key regex


def test_shard_key_regex_fans_out():
    inner = _RecordingPlanner("in")
    matcher = lambda fs: [  # noqa: E731
        (Equals("_ws_", "demo"), Equals("_ns_", "app1")),
        (Equals("_ws_", "demo"), Equals("_ns_", "app2")),
    ]
    skr = ShardKeyRegexPlanner(inner, matcher)
    p = _plan('sum(rate(foo{_ws_="demo",_ns_=~"app.*"}[5m]))')
    out = skr.materialize(p, QueryContext())
    assert isinstance(out, MultiPartitionReduceAggregateExec)
    assert len(inner.materialized) == 2
    for sub, ns in zip(inner.materialized, ("app1", "app2")):
        fs = pu.get_raw_series_filters(sub)[0]
        assert Equals("_ns_", ns) in fs
        assert not any(isinstance(f, EqualsRegex) and f.column == "_ns_"
                       for f in fs)


def test_shard_key_equals_passthrough():
    inner = _RecordingPlanner("in")
    skr = ShardKeyRegexPlanner(inner, lambda fs: [])
    p = _plan('sum(rate(foo{_ws_="demo",_ns_="app1"}[5m]))')
    out = skr.materialize(p, QueryContext())
    assert out.tag == "in"


def test_shard_key_regex_join_sides_fan_out_independently():
    inner = _RecordingPlanner("in")

    def matcher(fs):
        # expand only the regex side's namespaces
        return [(Equals("_ws_", "demo"), Equals("_ns_", "app1")),
                (Equals("_ws_", "demo"), Equals("_ns_", "app2"))]

    skr = ShardKeyRegexPlanner(inner, matcher)
    p = _plan('(sum(rate(foo{_ws_="demo",_ns_=~"app.*"}[5m]))'
              ' + sum(rate(bar{_ws_="demo",_ns_="other"}[5m])))')
    skr.materialize(p, QueryContext())
    # rhs (concrete _ns_="other") must NOT be rewritten with lhs combos
    rhs_plans = [m for m in inner.materialized
                 if any(Equals("_metric_", "bar") in fg or
                        any(getattr(f, "value", None) == "bar" for f in fg)
                        for fg in pu.get_raw_series_filters(m))]
    assert rhs_plans, "rhs side was never materialized"
    for m in rhs_plans:
        for fg in pu.get_raw_series_filters(m):
            assert Equals("_ns_", "other") in fg


def test_multi_partition_same_partition_two_windows():
    local = _RecordingPlanner("local")
    start_ms = START_S * 1000
    prov = _Provider([
        PartitionAssignment("remote-p", "http://p2/api",
                            TimeRange(start_ms, start_ms + 1_200_000)),
        PartitionAssignment("local", "",
                            TimeRange(start_ms + 1_260_000,
                                      start_ms + 2_400_000)),
        PartitionAssignment("remote-p", "http://p2/api",
                            TimeRange(start_ms + 2_460_000,
                                      start_ms + 10**9)),
    ])
    mp = MultiPartitionPlanner(prov, "local", local)
    p = _plan('foo{job="x"}')
    out = mp.materialize(p, QueryContext())
    remotes = [c for c in out.children if isinstance(c, PromQlRemoteExec)]
    assert len(remotes) == 2, "second remote-p window was dropped"
    assert remotes[1].end_ms == p.end_ms


def test_multi_partition_reduce_aggregate_compose():
    k1 = RangeVectorKey.make({"job": "x"})
    k2 = RangeVectorKey.make({"job": "y"})
    wends = np.asarray([1000, 2000], dtype=np.int64)
    b1 = ResultBlock([k1, k2], wends, np.asarray([[1.0, 2.0],
                                                  [np.nan, 5.0]]))
    b2 = ResultBlock([k1], wends, np.asarray([[10.0, np.nan]]))
    ex = MultiPartitionReduceAggregateExec(QueryContext(), [], "sum")
    out = ex.compose([b1, b2], QueryStats())
    vals = {k: v for k, v in zip(out.keys, np.asarray(out.values))}
    assert vals[k1][0] == 11.0 and vals[k1][1] == 2.0
    assert np.isnan(vals[k2][0]) and vals[k2][1] == 5.0


def test_at_modifier_survives_time_range_copy_and_unparse():
    """@ plans: copy_with_time_range must keep the pinned inner grid, and
    unparse must emit valid PromQL for remote routing (HA/multi-partition)."""
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    from filodb_tpu.query import planutils as pu
    from filodb_tpu.query import logical as lp

    T = TimeStepParams(1_600_000_600, 60, 1_600_003_600)
    for q in ["foo @ 1600000000",
              "rate(foo[5m] @ 1600000000)",
              "max_over_time(foo[10m:1m] @ 1600000000)",
              "max_over_time(foo[10m:1m] offset 5m @ 1600000000)",
              "rate(foo[5m])[30m:1m] @ 1600000000"]:
        plan = query_range_to_logical_plan(q, T)
        assert isinstance(plan, lp.ApplyAtTimestamp), q
        moved = pu.copy_with_time_range(
            plan, pu.TimeRange(1_600_001_000_000, 1_600_002_000_000))
        assert moved.inner.start_ms == moved.inner.end_ms \
            == 1_600_000_000_000, q
        assert moved.start_ms == 1_600_001_000_000
        # unparse -> reparse round trip preserves the pinned time
        text = pu.unparse(plan)
        again = query_range_to_logical_plan(text, T)
        assert isinstance(again, lp.ApplyAtTimestamp), text
        assert again.inner.start_ms == plan.inner.start_ms, text


def test_at_modifier_long_time_range_routes_by_pinned_time():
    """LongTimeRangePlanner must route @ queries by the PINNED time: an @
    older than raw retention goes to the downsample cluster even when the
    outer grid is recent."""
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    from filodb_tpu.query.planners import LongTimeRangePlanner

    calls = []

    class _P:
        def __init__(self, name):
            self.name = name

        def materialize(self, plan, ctx):
            calls.append(self.name)
            return object()

    earliest_raw = 1_600_010_000_000
    planner = LongTimeRangePlanner(
        _P("raw"), _P("ds"), lambda: earliest_raw,
        lambda: earliest_raw + 3_600_000)
    T = TimeStepParams(1_600_020_000, 60, 1_600_023_000)  # recent outer grid
    old = query_range_to_logical_plan("foo @ 1600000000", T)   # pinned OLD
    recent = query_range_to_logical_plan(
        f"foo @ {earliest_raw // 1000 + 600}", T)
    planner.materialize(old, QueryContext())
    planner.materialize(recent, QueryContext())
    assert calls == ["ds", "raw"]


def test_at_sentinels_resolve_to_top_level_bounds():
    """start()/end() inside subqueries resolve to the OUTERMOST query
    bounds (PromQL), not the shifted inner conversion range."""
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    from filodb_tpu.query import logical as lp

    T = TimeStepParams(1_600_000_600, 60, 1_600_003_600)
    plan = query_range_to_logical_plan(
        "max_over_time((foo @ start())[30m:1m])", T)
    # find the nested ApplyAtTimestamp and check it pins to query start
    def find(p):
        if isinstance(p, lp.ApplyAtTimestamp):
            return p
        for f in p.__dataclass_fields__:
            v = getattr(p, f)
            if isinstance(v, lp.LogicalPlan):
                r = find(v)
                if r is not None:
                    return r
        return None
    at = find(plan)
    assert at is not None
    assert at.inner.start_ms == 1_600_000_600_000


def test_at_modifier_wrapped_aggregate_routes_by_pinned_time():
    """sum(foo @ t) — pin NOT at the plan root — must still route by the
    pinned data time (the pin detector walks the whole tree)."""
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    from filodb_tpu.query.planners import LongTimeRangePlanner

    calls = []

    class _P:
        def __init__(self, name):
            self.name = name

        def materialize(self, plan, ctx):
            calls.append(self.name)
            return object()

    earliest_raw = 1_600_010_000_000
    planner = LongTimeRangePlanner(
        _P("raw"), _P("ds"), lambda: earliest_raw,
        lambda: earliest_raw + 3_600_000)
    T = TimeStepParams(1_600_020_000, 60, 1_600_023_000)
    old = query_range_to_logical_plan("sum(foo @ 1600000000)", T)
    planner.materialize(old, QueryContext())
    assert calls == ["ds"]


def test_at_modifier_pinned_data_range_includes_subquery_window():
    """pinned_data_range must account for a pinned subquery's full
    reach-back (window + lookback), not just the pinned instant."""
    plan = _plan("max_over_time(foo[2h:1m] @ 1600000000)")
    dr = lp.pinned_data_range(plan, 300_000)
    at = 1_600_000_000_000
    assert dr[1] == at
    assert dr[0] == at - 2 * 3600_000 - 300_000


def test_ha_planner_routes_pinned_failures_remote():
    """A local failure window covering the pinned @ time must send the
    whole query to the replica, even when the outer grid is healthy."""
    at_ms = 1_600_000_000_000
    fail = FailureTimeRange("local", TimeRange(at_ms - 600_000,
                                               at_ms + 600_000),
                            is_remote=False)
    local = _RecordingPlanner("local")
    T2 = TimeStepParams(START_S + 7200, 60, START_S + 10800)
    planner = HighAvailabilityPlanner("prometheus", local, _FP([fail]),
                                      "http://replica")
    out = planner.materialize(_plan("foo @ 1600000000", T2), QueryContext())
    assert isinstance(out, PromQlRemoteExec)
    assert not local.materialized
    # healthy pinned time -> local
    out2 = planner.materialize(
        _plan(f"foo @ {at_ms // 1000 + 7200}", T2), QueryContext())
    assert isinstance(out2, _Dummy) and out2.tag == "local"


def test_multi_partition_pinned_spanning_partitions_errors():
    """A pinned (@) read whose data range spans partitions must raise,
    not silently evaluate locally with partial data (ADVICE r2)."""
    local = _RecordingPlanner("local")
    start_ms = START_S * 1000
    mid = start_ms + 1800_000
    prov = _Provider([
        PartitionAssignment("remote-p", "http://p2/api",
                            TimeRange(0, mid - 1)),
        PartitionAssignment("local", "", TimeRange(mid, start_ms + 10**9)),
    ])
    mp = MultiPartitionPlanner(prov, "local", local)
    p = _plan(f'rate(foo[5m] @ {START_S + 600})')
    with pytest.raises(ValueError, match="pinned"):
        mp.materialize(p, QueryContext())


def test_multi_partition_pinned_single_remote_still_routes():
    """A pinned read wholly inside one remote partition routes there."""
    local = _RecordingPlanner("local")
    start_ms = START_S * 1000
    prov = _Provider([
        PartitionAssignment("remote-p", "http://p2/api",
                            TimeRange(0, start_ms + 10**9)),
    ])
    mp = MultiPartitionPlanner(prov, "local", local)
    p = _plan(f'rate(foo[5m] @ {START_S + 600})')
    out = mp.materialize(p, QueryContext())
    assert isinstance(out, PromQlRemoteExec)
