"""Multi-tenant QoS (PR 14): the weighted-fair scheduler, adaptive
read-side shedding, result-cache tenant quotas, and shuffle sharding.

The edge-case matrix the ISSUE names explicitly:
  * share redistribution when a tenant goes idle mid-burst (and no
    credit banking while idle)
  * kill during tenant-queue wait releases the right queue slot
  * result-cache quota eviction never evicts another tenant's entry to
    fit an over-quota one
Plus: DRR honors weights under saturation, queue-full/deadline sheds
carry Retry-After and surface as HTTP 429, internal workspaces are
never shed, and the scan-limit 429s answer like the ingest ones.
"""
import collections
import threading
import time

import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.query.activequeries import (CancellationToken,
                                            active_queries, verdict_of)
from filodb_tpu.query.qos import (Admission, WeightedFairScheduler,
                                  account_wait, shuffle_shard_nodes)
from filodb_tpu.query.rangevector import QueryResult
from filodb_tpu.utils.usage import UsageAccountant


# ------------------------------------------------------- DRR mechanics


def _saturate(sched, shares_of_tenants, dur_s=1.2, workers_per=3,
              work_s=0.002):
    """Saturating workers per tenant; returns grant counts."""
    grants = collections.Counter()
    stop = threading.Event()

    def worker(ws):
        while not stop.is_set():
            adm = sched.admit(ws, 5.0)
            if adm.acquired:
                grants[ws] += 1
                time.sleep(work_s)
                sched.release(ws)

    threads = [threading.Thread(target=worker, args=(ws,))
               for ws in shares_of_tenants for _ in range(workers_per)]
    for t in threads:
        t.start()
    time.sleep(dur_s)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    return grants


def test_drr_equal_shares_split_evenly():
    sched = WeightedFairScheduler(1, shed_enabled=False)
    g = _saturate(sched, ["a", "b", "c"])
    lo, hi = min(g.values()), max(g.values())
    assert lo > 0 and hi / lo < 1.3


def test_drr_weighted_shares_honored():
    """A share of 3 is worth ~3x the grants of a share of 1 under
    saturation — the bug class where rotation hands every tenant one
    grant per round regardless of weight."""
    sched = WeightedFairScheduler(1, shares={"big": 3.0},
                                  shed_enabled=False)
    g = _saturate(sched, ["big", "small"])
    ratio = g["big"] / max(g["small"], 1)
    assert 2.2 < ratio < 4.0, g


def test_share_redistribution_when_tenant_goes_idle_mid_burst():
    """Mid-burst, one tenant stops: the other's grant rate must absorb
    the freed share (work conservation), and the returning tenant must
    NOT burst past its share on banked deficit."""
    sched = WeightedFairScheduler(1, shed_enabled=False)
    counts = collections.Counter()
    stop_b = threading.Event()
    stop_all = threading.Event()

    def worker(ws, stop_mine):
        while not (stop_all.is_set() or stop_mine.is_set()):
            adm = sched.admit(ws, 5.0)
            if adm.acquired:
                counts[ws] += 1
                time.sleep(0.002)
                sched.release(ws)

    threads = [threading.Thread(target=worker,
                                args=("a", threading.Event()))
               for _ in range(2)]
    threads += [threading.Thread(target=worker, args=("b", stop_b))
                for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.6)
    phase1 = dict(counts)
    stop_b.set()                          # b goes idle mid-burst
    time.sleep(0.3)                       # let b's queue drain fully
    a_mark = counts["a"]
    t0 = time.monotonic()
    time.sleep(0.6)
    a_rate_solo = (counts["a"] - a_mark) / (time.monotonic() - t0)
    stop_all.set()
    for t in threads:
        t.join(timeout=5)
    # phase 1 split roughly evenly...
    assert phase1["a"] > 0 and phase1["b"] > 0
    assert phase1["b"] / phase1["a"] > 0.6
    # ...and a's solo rate absorbed b's share (≈ 2x its shared rate)
    a_rate_shared = phase1["a"] / 0.6
    assert a_rate_solo > 1.5 * a_rate_shared
    # b forfeited its banked deficit while idle: the scheduler's
    # rotation no longer contains it and its deficit is gone
    assert "b" not in sched._order
    assert "b" not in sched._deficit


def test_kill_during_tenant_queue_wait_releases_right_slot():
    """A cancelled waiter leaves ITS tenant queue (not another's), the
    slot is never held, and a follow-up admit for the same tenant goes
    straight through once capacity frees."""
    sched = WeightedFairScheduler(1)
    hold = sched.admit("hog", 1.0)
    assert hold.acquired
    tok = CancellationToken()
    other_queued = threading.Event()

    def other():
        # an innocent bystander queued under a different tenant
        other_queued.set()
        adm = sched.admit("bystander", 5.0)
        assert adm.acquired
        sched.release("bystander")

    t_other = threading.Thread(target=other)
    t_other.start()
    other_queued.wait(1.0)
    time.sleep(0.05)
    got = {}

    def victim():
        got["adm"] = sched.admit("victim", 5.0, tok=tok)

    t = threading.Thread(target=victim)
    t.start()
    time.sleep(0.15)                      # victim is queued
    assert sched.queue_depths().get("victim") == 1
    tok.cancel("admin")
    t.join(timeout=2)
    assert not t.is_alive()
    assert got["adm"].status == "cancelled"
    # the RIGHT queue slot was released: victim's queue is empty, the
    # bystander still waits (then gets the slot on release)
    assert sched.queue_depths().get("victim", 0) == 0
    assert sched.queue_depths().get("bystander") == 1
    sched.release("hog")
    t_other.join(timeout=2)
    assert not t_other.is_alive()


def test_shed_on_queue_full_with_retry_after():
    sched = WeightedFairScheduler(1, max_queue_depth=1)
    assert sched.admit("t", 1.0).acquired

    def queued():
        adm = sched.admit("t", 5.0)
        if adm.acquired:
            sched.release("t")

    t = threading.Thread(target=queued)
    t.start()
    time.sleep(0.1)                       # fill the depth-1 queue
    adm = sched.admit("t", 1.0)
    assert adm.status == "shed" and adm.reason == "queue_full"
    assert adm.retry_after_s > 0
    assert "tenant_overloaded" in adm.shed_error()
    sched.release("t")
    t.join(timeout=2)


def test_shed_on_predicted_deadline_blowout():
    sched = WeightedFairScheduler(1, max_queue_depth=0)
    sched._hold_ewma_s = 10.0             # recent queries held 10 s
    assert sched.admit("hog", 1.0).acquired
    adm = sched.admit("t", 1.0, deadline_unix_s=time.time() + 0.5)
    assert adm.status == "shed" and adm.reason == "deadline"
    assert adm.retry_after_s > 0.5
    sched.release("hog")


def test_internal_workspaces_never_shed():
    """_rules_/_self_ schedule like anyone but are exempt from the shed
    gate — the ruler must not be starved out of its standing queries by
    the very overload it alerts on."""
    sched = WeightedFairScheduler(1, max_queue_depth=1)
    sched._hold_ewma_s = 100.0
    assert sched.admit("hog", 1.0).acquired
    adm = sched.admit("_rules_", 0.05,
                      deadline_unix_s=time.time() + 0.01)
    # not shed: it waited (and timed out) instead
    assert adm.status == "timeout"
    sched.release("hog")


def test_hostile_ws_churn_folds_into_overflow():
    """ws comes from client-controlled query text: past MAX_TENANTS
    distinct workspaces the scheduler folds strangers into the overflow
    sentinel — its tables (and the metric cardinality keyed off
    Admission.ws) stay bounded."""
    from filodb_tpu.utils.usage import OVERFLOW_TENANT
    sched = WeightedFairScheduler(4)
    for i in range(sched.MAX_TENANTS + 40):
        adm = sched.admit(f"ws{i}", 1.0)
        assert adm.acquired
        sched.release(adm.ws)
    assert len(sched._seen) == sched.MAX_TENANTS
    adm = sched.admit("one-more-stranger", 1.0)
    assert adm.ws == OVERFLOW_TENANT[0]
    sched.release(adm.ws)
    # zeroed/empty rows are dropped, not accumulated per ws ever seen
    assert not sched._active and not sched._queues


def test_result_cache_partial_hit_survives_shed_tail():
    """A shed tail run must NOT drop the still-valid warm prefix nor
    trigger a second full run through the gate that just shed it."""
    from filodb_tpu.query.resultcache import ResultCache
    from filodb_tpu.query.rangevector import QueryStats, ResultBlock
    cache = ResultCache()
    token, horizon = ("t",), 10 * 60_000
    calls = []

    def ok_run(s, e):
        calls.append((s, e))
        wends = np.arange(s * 1000, e * 1000 + 1, 60_000)
        from filodb_tpu.query.rangevector import RangeVectorKey
        k = RangeVectorKey((("x", "1"),))
        return QueryResult([ResultBlock(
            [k], wends, np.ones((1, wends.size)))], QueryStats())

    res1 = cache.query_range(ok_run, "up", 0, 60, 300, "pp",
                             (token, horizon))
    assert res1.error is None and len(cache) == 1

    def shed_run(s, e):
        calls.append((s, e))
        r = QueryResult([], error="tenant_overloaded: queue full")
        r.retry_after_s = 1.0
        return r

    res2 = cache.query_range(shed_run, "up", 0, 60, 600, "pp",
                             (token, horizon))
    assert res2.error.startswith("tenant_overloaded")
    assert len(cache) == 1                # warm prefix kept
    # exactly ONE run attempt for the shed poll (the tail), no full
    # recompute through the shedding gate
    assert len(calls) == 2 and calls[0] == (0, 300)
    assert calls[1][1] == 600 and calls[1][0] > 0


def test_account_wait_single_home():
    res = QueryResult([])
    account_wait(res, Admission("shed", waited_s=0.25))
    account_wait(res, None)               # no scheduler: no-op
    account_wait(None, Admission("acquired", waited_s=1.0))
    assert res.stats.queue_wait_s == pytest.approx(0.25)


def test_verdict_of_shed():
    assert verdict_of(QueryResult(
        [], error="tenant_overloaded: queue full")) == "shed"


# --------------------------------------------------- frontend + routes


def _store_frontend(cfg=None, series=24):
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.frontend import QueryFrontend
    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(
        counter_batch(series, 120, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    if cfg is None:
        cfg = FilodbSettings()
    return QueryFrontend(eng, config=cfg), eng, START // 1000


def test_frontend_shed_surfaces_structured_error_and_slowlog():
    from filodb_tpu.utils.slowlog import slowlog
    from filodb_tpu.utils.usage import usage
    usage.clear()
    slowlog.clear()
    cfg = FilodbSettings()
    cfg.query.max_concurrent_queries = 1
    cfg.query.tenant_max_queue_depth = 1
    cfg.query.result_cache_enabled = False
    cfg.query.singleflight_enabled = False
    fe, eng, s = _store_frontend(cfg)
    q = 'sum(rate(request_total{_ws_="demo"}[5m]))'
    # hold the only slot and fill demo's queue
    assert fe.scheduler.admit("hog", 1.0).acquired
    done = threading.Event()

    def queued():
        fe.query_range(q, s + 600, 60, s + 1190)
        done.set()

    t = threading.Thread(target=queued)
    t.start()
    time.sleep(0.15)
    try:
        res = fe.query_range(q, s + 600, 60, s + 1190)
    finally:
        fe.scheduler.release("hog")
    t.join(timeout=5)
    assert done.is_set()
    assert res.error is not None
    assert res.error.split(":", 1)[0] == "tenant_overloaded"
    assert getattr(res, "retry_after_s", 0.0) > 0
    assert verdict_of(res) == "shed"
    # force-recorded in the slowlog with verdict shed, tenant attributed
    recs = [r for r in slowlog.entries() if r["verdict"] == "shed"]
    assert recs and recs[-1]["tenant"]["ws"] == "demo"
    usage.clear()


def test_http_shed_and_scan_limit_answer_429_with_retry_after():
    from filodb_tpu.http.routes import PromHttpApi
    from filodb_tpu.utils.usage import usage
    usage.clear()
    cfg = FilodbSettings()
    cfg.query.max_concurrent_queries = 1
    cfg.query.tenant_max_queue_depth = 0
    cfg.query.result_cache_enabled = False
    cfg.query.singleflight_enabled = False
    fe, eng, s = _store_frontend(cfg)
    api = PromHttpApi({"prometheus": eng}, config=cfg)
    fe = api.frontends["prometheus"]
    # deadline-based shed: recent holds are long, budget is short
    fe.scheduler._hold_ewma_s = 100.0
    assert fe.scheduler.admit("hog", 1.0).acquired
    try:
        st, payload = api.handle(
            "GET", "/api/v1/query_range",
            {"query": 'sum(rate(request_total{_ws_="demo"}[5m]))',
             "start": str(s + 600), "end": str(s + 1190), "step": "60",
             "timeout": "1"})
    finally:
        fe.scheduler.release("hog")
    assert st == 429
    assert payload["errorType"] == "too_many_requests"
    assert int(payload["_headers"]["Retry-After"]) >= 1
    # scan-limit rejection: same 429 + Retry-After contract
    cfg2 = FilodbSettings()
    cfg2.query.tenant_samples_fail_limit = 10
    fe2, eng2, s2 = _store_frontend(cfg2)
    api2 = PromHttpApi({"prometheus": eng2}, config=cfg2)
    q = {"query": 'sum(rate(request_total{_ws_="demo"}[5m]))',
         "start": str(s2 + 600), "end": str(s2 + 1190), "step": "60"}
    st1, _ = api2.handle("GET", "/api/v1/query_range", dict(q))
    assert st1 == 200                     # the crossing query runs
    st2, pay2 = api2.handle("GET", "/api/v1/query_range", dict(q))
    assert st2 == 429
    assert pay2["error"].startswith("tenant_limit_exceeded")
    ra = int(pay2["_headers"]["Retry-After"])
    assert 1 <= ra <= int(cfg2.query.tenant_limit_window_s) + 1
    usage.clear()


def test_admin_tenants_payload():
    from filodb_tpu.http.routes import PromHttpApi
    from filodb_tpu.utils.usage import usage
    usage.clear()
    cfg = FilodbSettings()
    cfg.query.tenant_shares = {"demo": 2.5}
    fe, eng, s = _store_frontend(cfg)
    api = PromHttpApi({"prometheus": eng}, config=cfg)
    r = api.frontends["prometheus"].query_range(
        'sum(rate(request_total{_ws_="demo"}[5m]))',
        s + 600, 60, s + 1190)
    assert r.error is None
    st, payload = api.handle("GET", "/admin/tenants", {})
    assert st == 200
    rows = {t["ws"]: t for t in payload["data"]["tenants"]}
    assert rows["demo"]["queries"] >= 1
    assert rows["demo"]["share"] == 2.5
    assert rows["demo"]["queued"] == 0
    usage.clear()


def test_scan_retry_after_tracks_window():
    acc = UsageAccountant(window_s=30.0)
    acc.record_query("w", "n", 0.1, 1000, 10)
    assert acc.admit("w", "n", 0, 50) is not None
    ra = acc.scan_retry_after("w", "n")
    assert 0 < ra <= 30.0
    # unknown tenants answer a tiny positive hint, never a crash
    assert acc.scan_retry_after("nobody", "") > 0


# ------------------------------------------------- result-cache quotas


def _entry(nbytes):
    from filodb_tpu.query.resultcache import _Entry
    wends = np.arange(1, 3, dtype=np.int64) * 60_000
    return _Entry(wends, {}, int(wends[-1]), ("tok",), nbytes)


def test_result_cache_tenant_quota_evicts_own_entries_only():
    from filodb_tpu.query.resultcache import ResultCache
    cache = ResultCache(max_entries=64, max_entry_bytes=1 << 20,
                        tenant_quota_bytes=100)

    def key(ws, i):
        return (f'up{{_ws_="{ws}",x="{i}"}}', 60_000, 0, "pp")

    cache._insert(key("a", 1), _entry(40))
    cache._insert(key("a", 2), _entry(40))
    cache._insert(key("b", 1), _entry(40))
    assert len(cache) == 3
    # a's third entry pushes a over quota: a's OLDEST goes, b survives
    cache._insert(key("a", 3), _entry(40))
    assert key("a", 1) not in cache._entries
    assert key("a", 2) in cache._entries
    assert key("a", 3) in cache._entries
    assert key("b", 1) in cache._entries
    assert cache.tenant_bytes("a") == 80
    assert cache.tenant_bytes("b") == 40


def test_result_cache_over_quota_entry_rejected_not_fitted():
    """An entry bigger than the quota must be REJECTED — never evict
    another tenant's entries (or even all of your own) to fit it."""
    from filodb_tpu.query.resultcache import ResultCache
    cache = ResultCache(max_entries=64, max_entry_bytes=1 << 20,
                        tenant_quota_bytes=100)
    cache._insert(('up{_ws_="b"}', 60_000, 0, "pp"), _entry(40))
    cache._insert(('up{_ws_="a"}', 60_000, 0, "pp"), _entry(240))
    assert ('up{_ws_="a"}', 60_000, 0, "pp") not in cache._entries
    assert cache.tenant_bytes("b") == 40
    assert len(cache) == 1


def test_result_cache_quota_disabled_keeps_global_lru():
    from filodb_tpu.query.resultcache import ResultCache
    cache = ResultCache(max_entries=2, max_entry_bytes=1 << 20,
                        tenant_quota_bytes=0)
    for i in range(3):
        cache._insert((f'up{{x="{i}"}}', 60_000, 0, "pp"), _entry(40))
    assert len(cache) == 2                # plain LRU cap


# ----------------------------------------------------- shuffle sharding


def test_shuffle_shard_nodes_deterministic_k_of_n():
    nodes = [f"n{i}" for i in range(8)]
    a1 = shuffle_shard_nodes("tenantA", nodes, 2)
    a2 = shuffle_shard_nodes("tenantA", list(reversed(nodes)), 2)
    assert a1 == a2 and len(a1) == 2      # order-independent, stable
    subsets = {shuffle_shard_nodes(f"t{i}", nodes, 2) for i in range(30)}
    assert len(subsets) > 5               # tenants spread across subsets
    assert shuffle_shard_nodes("t", nodes, 0) == tuple(sorted(nodes))
    assert shuffle_shard_nodes("t", nodes, 99) == tuple(sorted(nodes))


def test_failover_dispatcher_prefers_tenant_subset():
    from filodb_tpu.query.execbase import PlanDispatcher, QueryError
    from filodb_tpu.query.rangevector import QueryContext
    from filodb_tpu.replication.failover import ReplicaFailoverDispatcher

    calls = []

    class _D(PlanDispatcher):
        def __init__(self, name, fail=False):
            self.name, self.fail = name, fail

        def dispatch(self, plan, source):
            calls.append(self.name)
            if self.fail:
                raise QueryError("shard_unavailable", self.name)
            return f"ok:{self.name}"

    class _Plan:
        def __init__(self):
            self.ctx = QueryContext()

    nodes = ["n0", "n1", "n2", "n3"]
    # find a tenant whose k=1 subset is NOT the primary n0, so the
    # reorder is observable
    ws = next(w for w in (f"w{i}" for i in range(64))
              if shuffle_shard_nodes(w, nodes, 1)[0] != "n0")
    pref = shuffle_shard_nodes(ws, nodes, 1)[0]
    targets = [(n, _D(n)) for n in nodes]
    disp = ReplicaFailoverDispatcher(targets, shard=0, all_nodes=nodes,
                                     shuffle_k=1)
    plan = _Plan()
    plan.ctx.tenant_ws = ws
    assert disp.dispatch(plan, None) == f"ok:{pref}"
    assert calls == [pref]
    # failover is preserved: a dead preferred node falls through in the
    # reordered walk (preferred first, everyone else still a fallback)
    calls.clear()
    targets2 = [(n, _D(n, fail=(n == pref))) for n in nodes]
    disp2 = ReplicaFailoverDispatcher(targets2, shard=0, all_nodes=nodes,
                                      shuffle_k=1)
    out = disp2.dispatch(plan, None)
    assert out.startswith("ok:") and calls[0] == pref and len(calls) == 2
    # no tenant on the context -> assignment order untouched
    calls.clear()
    plain = _Plan()
    assert disp.dispatch(plain, None) == "ok:n0"
    assert calls == ["n0"]
