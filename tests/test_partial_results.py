"""Failure-domain hardening (PR 4): partial results on node death,
per-peer circuit breakers, end-to-end deadlines, and the
never-cache-partials contract.  In-process "kills" (NodeQueryServer.stop
-> connection refused) give the same socket-level failure signature as a
SIGKILL without subprocess cost; the chaos bench (`python bench.py
chaos`) covers the real-SIGKILL macro run."""
import socket
import threading
import time

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.ingest.generator import counter_batch, gauge_batch
from filodb_tpu.parallel.breaker import breakers
from filodb_tpu.parallel.shardmapper import SpreadProvider
from filodb_tpu.parallel.testcluster import make_two_node_cluster
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.rangevector import PlannerParams

START = 1_600_000_020_000
S = START // 1000
Q = 'sum by (_ns_)(rate(request_total[5m]))'


@pytest.fixture(autouse=True)
def _fresh_breakers():
    breakers.reset()
    breakers.configure(failure_threshold=3, open_base_s=0.2,
                       open_max_s=1.0, jitter=0.0)
    yield
    breakers.configure()
    breakers.reset()


@pytest.fixture()
def cluster():
    c = make_two_node_cluster(
        [counter_batch(40, 360, start_ms=START),
         gauge_batch(30, 360, start_ms=START)], with_truth=True)
    truth_eng = QueryEngine("prometheus", c.truth, c.mapper,
                            SpreadProvider(default_spread=1))
    yield c, truth_eng
    c.stop()


# ------------------------------------------------------ partial results


def test_kill_node_mid_scatter_partial_flag_and_surviving_data(cluster):
    c, truth_eng = cluster
    pp = PlannerParams(allow_partial_results=True)
    # healthy first: full result, not partial
    healthy = c.engine.query_range(Q, S + 600, 60, S + 3600, pp)
    assert healthy.error is None and healthy.partial is False

    c.servers["nodeB"].stop()           # shards 2,3 now unreachable

    res = c.engine.query_range(Q, S + 600, 60, S + 3600, pp)
    assert res.error is None, res.error
    assert res.partial is True
    assert res.stats.partial is True
    assert any("shard dropped" in w for w in res.stats.warnings)

    # surviving data is CORRECT: exactly what the truth engine computes
    # over the surviving shards (0,1 — nodeA's)
    expect = truth_eng.query_range(
        Q, S + 600, 60, S + 3600, PlannerParams(shard_overrides=[0, 1]))
    assert expect.error is None
    got = {k: v for k, _, v in res.series()}
    want = {k: v for k, _, v in expect.series()}
    assert set(got) == set(want) and len(got) > 0
    for k in got:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-9,
                                   equal_nan=True)

    # the Prometheus envelope carries the flag + warnings, never silent
    payload = QueryEngine.to_prom_matrix(res)
    assert payload["partial"] is True
    assert payload["warnings"]
    # and ?stats=true exposes them too
    d = res.stats.to_dict()
    assert d["partial"] is True and d["warnings"]


def test_without_gate_node_death_fails_with_typed_error(cluster):
    c, _ = cluster
    c.servers["nodeB"].stop()
    res = c.engine.query_range(Q, S + 600, 60, S + 3600)
    assert res.error is not None
    assert res.error.startswith("shard_unavailable")
    assert res.partial is False


def test_raw_selector_partial_keeps_per_series_values(cluster):
    """Raw (unaggregated) partials: the surviving series' VALUES are
    bit-identical to the full-truth result — a dropped shard may only
    remove series, never corrupt survivors."""
    c, truth_eng = cluster
    pp = PlannerParams(allow_partial_results=True)
    c.servers["nodeB"].stop()
    res = c.engine.query_range('heap_usage', S + 600, 60, S + 3600, pp)
    assert res.error is None and res.partial is True
    full = truth_eng.query_range('heap_usage', S + 600, 60, S + 3600)
    got = {k: v for k, _, v in res.series()}
    want = {k: v for k, _, v in full.series()}
    assert 0 < len(got) < len(want)     # strictly partial
    for k, v in got.items():
        np.testing.assert_allclose(v, want[k], rtol=1e-9, equal_nan=True)


# ----------------------------------------------------- circuit breakers


def _mk_leaf(shard=0):
    from filodb_tpu.core.index import Equals
    from filodb_tpu.query.exec import (AggregateMapReduce,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper)
    from filodb_tpu.query.rangevector import QueryContext
    plan = MultiSchemaPartitionsExec(
        QueryContext(query_id="qb"), "prometheus", shard,
        [Equals("_metric_", "request_total")], START, START + 3_600_000)
    plan.add_transformer(PeriodicSamplesMapper(
        START + 600_000, 60_000, START + 3_600_000, 300_000, "rate", ()))
    plan.add_transformer(AggregateMapReduce("sum", (), (), ()))
    return plan


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_breaker_opens_fails_fast_half_opens_and_recovers():
    from filodb_tpu.parallel.transport import (NodeQueryServer,
                                               RemoteNodeDispatcher)
    from filodb_tpu.query.execbase import QueryError

    port = _free_port()                 # nothing listening: refused
    # generous ask timeout: the revived server pays a cold XLA compile
    # on the probe dispatch; a timeout would (correctly) re-open via
    # on_abort, which is not what this test is probing
    disp = RemoteNodeDispatcher("127.0.0.1", port, timeout_s=30.0)
    peer = f"127.0.0.1:{port}"

    # threshold consecutive connect failures -> open
    for _ in range(3):
        with pytest.raises(QueryError) as ei:
            disp.dispatch(_mk_leaf(), None)
        assert ei.value.code == "shard_unavailable"
    br = breakers.get(peer)
    assert br.state == "open"

    # open: fail-fast in microseconds, no socket touched
    t0 = time.perf_counter()
    with pytest.raises(QueryError) as ei:
        disp.dispatch(_mk_leaf(), None)
    assert time.perf_counter() - t0 < 0.05
    assert "circuit open" in str(ei.value)
    assert ei.value.code == "shard_unavailable"
    assert br.fail_fast >= 1

    # half-open probe against the still-dead peer -> re-open, doubled
    time.sleep(0.25)
    with pytest.raises(QueryError):
        disp.dispatch(_mk_leaf(), None)     # the admitted probe
    assert br.state == "open"
    assert br.snapshot()["backoffSeconds"] == pytest.approx(0.4)

    # peer comes back on the SAME address: probe succeeds -> closed
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    srv = NodeQueryServer(ms, port=port).start()
    try:
        time.sleep(0.45)
        data, stats = disp.dispatch(_mk_leaf(), None)
        assert stats.samples_scanned > 0
        assert br.state == "closed"
        assert br.consecutive_failures == 0
    finally:
        srv.stop()


def test_breaker_probe_timeout_releases_slot_never_wedges():
    """Regression (found by the chaos stage): a half-open probe whose
    dispatch ends in a TIMEOUT — no liveness verdict — must release the
    probe slot via on_abort (re-opening, doubled backoff).  Before the
    fix the slot leaked and the breaker stayed half-open forever,
    failing fast on a recovered peer."""
    from filodb_tpu.parallel.breaker import CircuitBreaker
    br = CircuitBreaker("peer:1", failure_threshold=1, open_base_s=0.05,
                        open_max_s=1.0, jitter=0.0)
    br.on_failure()
    assert br.state == "open"
    time.sleep(0.07)
    assert br.allow() is True           # the half-open probe
    assert br.allow() is False          # slot held while it runs
    br.on_abort()                       # probe timed out
    assert br.state == "open"
    assert br.snapshot()["backoffSeconds"] == pytest.approx(0.1)
    time.sleep(0.12)
    assert br.allow() is True           # a NEW probe is admitted
    br.on_success()
    assert br.state == "closed"
    # on_abort on a CLOSED breaker is a no-op (plain dispatch timeout)
    br.on_abort()
    assert br.state == "closed"


def test_breaker_fail_fast_engages_partial_path(cluster):
    """With nodeB's breaker already open, a gated query degrades to a
    partial WITHOUT paying any socket work for the dead peer."""
    c, _ = cluster
    c.servers["nodeB"].stop()
    pp = PlannerParams(allow_partial_results=True)
    # first query: opens the breaker via real connect failures (threshold
    # 3; the engine's initial attempt + partial re-execution provide them)
    for _ in range(3):
        c.engine.query_range(Q, S + 600, 60, S + 3600, pp)
    dead_peer = "%s:%d" % c.servers["nodeB"].address
    assert breakers.get(dead_peer).state == "open"
    t0 = time.perf_counter()
    res = c.engine.query_range(Q, S + 600, 60, S + 3600, pp)
    dur = time.perf_counter() - t0
    assert res.error is None and res.partial is True
    assert breakers.get(dead_peer).fail_fast > 0
    assert dur < 2.0                    # no connect-timeout serialization


# ----------------------------------------------------------- deadlines


def test_expired_deadline_returns_structured_error_with_stats():
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    pp = PlannerParams(deadline_unix_s=time.time() - 1.0)
    res = eng.query_range(Q, S + 600, 60, S + 3600, pp)
    assert res.error is not None
    assert res.error.startswith("query_timeout")
    # the structured envelope: errorType timeout + per-phase stats
    payload = QueryEngine.to_prom_matrix(res)
    assert payload["status"] == "error"
    assert payload["errorType"] == "timeout"
    assert "phases" in res.stats.to_dict()


def test_deadline_expiry_in_scheduler_queue_attributes_queue_wait():
    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.query.frontend import QueryFrontend

    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    cfg = FilodbSettings()
    cfg.query.max_concurrent_queries = 1
    fe = QueryFrontend(eng, config=cfg)
    # hog the single execution slot so the query dies IN THE QUEUE
    # (the qos scheduler replaced the semaphore; an admit under another
    # tenant's name holds the one global capacity slot the same way)
    assert fe.scheduler.admit("hog", 1.0).acquired
    try:
        t0 = time.perf_counter()
        res = fe.query_range(Q, S + 600, 60, S + 3600,
                             PlannerParams(timeout_s=0.3))
        waited = time.perf_counter() - t0
    finally:
        fe.scheduler.release("hog")
    assert res.error is not None and res.error.startswith("query_timeout")
    assert "queue" in res.error
    # queue wait is attributed in the stats the error ships with
    assert res.stats.queue_wait_s == pytest.approx(waited, abs=0.15)
    assert res.stats.queue_wait_s >= 0.25


def test_remote_dispatch_timeout_bounded_by_remaining_budget():
    """A peer that ACCEPTS the plan but never replies: the socket wait is
    bounded by the query's remaining budget, and its expiry is the
    structured query_timeout (not a 120 s ask-timeout hang)."""
    from filodb_tpu.parallel.transport import RemoteNodeDispatcher
    from filodb_tpu.query.execbase import QueryError

    # a listener that accepts and then stays silent
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    accepted = []
    t = threading.Thread(
        target=lambda: accepted.append(lsock.accept()), daemon=True)
    t.start()
    try:
        disp = RemoteNodeDispatcher(*lsock.getsockname(), timeout_s=30.0)
        plan = _mk_leaf()
        plan.ctx.deadline_unix_s = time.time() + 0.4
        t0 = time.perf_counter()
        with pytest.raises(QueryError) as ei:
            disp.dispatch(plan, None)
        dur = time.perf_counter() - t0
        assert ei.value.code == "query_timeout"
        assert 0.2 < dur < 5.0          # budget-bounded, not ask-bounded
    finally:
        lsock.close()
        for conn, _ in accepted:
            conn.close()


def test_wedged_peer_deadline_share_yields_droppable_dispatch_timeout():
    """A wedged peer (accepts, never replies) under an ample deadline
    with partial results ALLOWED: the hop's socket wait is capped at the
    deadline SHARE (query.peer_deadline_share, default 0.5) of the
    remaining budget, so it expires as the droppable dispatch_timeout
    with budget left for the survivors — NOT as the non-droppable
    query_timeout after consuming the whole budget.  And a share-bounded
    expiry teaches the breaker nothing (a slow peer is not a dead one)."""
    from filodb_tpu.parallel.transport import RemoteNodeDispatcher
    from filodb_tpu.query.execbase import QueryError

    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    accepted = []
    t = threading.Thread(
        target=lambda: accepted.append(lsock.accept()), daemon=True)
    t.start()
    try:
        disp = RemoteNodeDispatcher(*lsock.getsockname(), timeout_s=30.0)
        plan = _mk_leaf()
        plan.ctx.planner_params = PlannerParams(allow_partial_results=True)
        dl = time.time() + 1.0
        plan.ctx.deadline_unix_s = dl
        t0 = time.perf_counter()
        with pytest.raises(QueryError) as ei:
            disp.dispatch(plan, None)
        dur = time.perf_counter() - t0
        assert ei.value.code == "dispatch_timeout"
        assert 0.3 < dur < 0.9          # the 0.5 share, not the full 1 s
        assert time.time() < dl         # survivors still have budget
        peer = "%s:%d" % lsock.getsockname()
        assert breakers.get(peer).consecutive_failures == 0
    finally:
        lsock.close()
        for conn, _ in accepted:
            conn.close()


def test_engine_caps_request_timeout_at_config_default(monkeypatch):
    """timeout_s above query.default_timeout_s is capped server-side."""
    from filodb_tpu import config as config_mod
    cfg = config_mod.FilodbSettings()
    cfg.query.default_timeout_s = 5.0
    monkeypatch.setattr(config_mod, "_SETTINGS", cfg)
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0)
    eng = QueryEngine("prometheus", ms)
    ctx = eng._ctx(PlannerParams(timeout_s=600.0))
    assert ctx.deadline_unix_s <= time.time() + 5.5
    # and a request SHRINKING the budget is honored
    ctx2 = eng._ctx(PlannerParams(timeout_s=0.5))
    assert ctx2.deadline_unix_s <= time.time() + 1.0


def test_singleflight_follower_does_not_inherit_leader_timeout():
    """Budgets are per-request and repr-excluded from the dedup key: a
    short-timeout leader whose budget expires must not fail a follower
    whose own budget is ample — the follower re-runs solo."""
    import threading

    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.query.frontend import QueryFrontend, _Flight
    from filodb_tpu.query.rangevector import QueryResult

    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    fe = QueryFrontend(QueryEngine("prometheus", ms),
                       config=FilodbSettings())
    pp = fe._admit_params(PlannerParams(timeout_s=60.0))
    # simulate an in-flight leader whose own (shorter) budget expired
    flight = _Flight()
    flight.result = QueryResult(
        [], error="query_timeout: deadline exceeded at RootExec")
    flight.done.set()
    key = (Q, S + 600, 60, S + 3600, repr(pp))
    with fe._sf_lock:
        fe._inflight[key] = flight
    try:
        res, shared = fe._singleflight(
            key, lambda: fe._cached_query(Q, S + 600, 60, S + 3600, pp),
            pp)
    finally:
        with fe._sf_lock:
            fe._inflight.pop(key, None)
    assert shared is False
    assert res.error is None            # solo re-run under OWN budget


def test_remote_query_timeout_code_survives_the_wire():
    """A deadline that expires ON the remote node must surface at the
    coordinator as query_timeout (errorType "timeout"), not be
    flattened into remote_failure."""
    from filodb_tpu.parallel.transport import (NodeQueryServer,
                                               RemoteNodeDispatcher)
    from filodb_tpu.query.execbase import QueryError
    from filodb_tpu.utils.faults import faults

    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    srv = NodeQueryServer(ms).start()
    try:
        disp = RemoteNodeDispatcher(*srv.address, timeout_s=10.0)
        plan = _mk_leaf()
        disp.dispatch(plan, None)               # warm node-side compiles
        plan2 = _mk_leaf()
        plan2.ctx.deadline_unix_s = time.time() + 0.25
        # delay the SEND past the deadline: the coordinator's pre-check
        # passes, the REMOTE's exec-boundary check fires
        with faults.plan("transport.send", "delay", first_k=1,
                         delay_s=0.4):
            with pytest.raises(QueryError) as ei:
                disp.dispatch(plan2, None)
        assert ei.value.code == "query_timeout"
        assert "via node" in str(ei.value)
    finally:
        faults.disarm()
        srv.stop()


def test_timeout_variants_share_serving_keys():
    """timeout_s / deadline / partial_now are repr-excluded: requests
    differing only in their budget must dedup in singleflight, the
    coalescer, and the result cache."""
    a = repr(PlannerParams())
    b = repr(PlannerParams(timeout_s=30.0, deadline_unix_s=123.0,
                           partial_now=True))
    assert a == b


def test_metadata_query_degrades_to_partial(cluster):
    from filodb_tpu.query import logical as lp
    c, truth_eng = cluster
    c.servers["nodeB"].stop()
    plan = lp.LabelValues(("_ns_",), (), 0, 1 << 62)
    # without the gate: typed error
    res = c.engine.exec_logical_plan(plan)
    assert res.error is not None and \
        res.error.startswith("shard_unavailable")
    # with the gate: survivors' label values, no hard error — and the
    # degradation is FLAGGED (a silently shortened label dropdown is
    # exactly the silent partial the contract forbids)
    res = c.engine.exec_logical_plan(
        plan, PlannerParams(allow_partial_results=True))
    assert res.error is None
    assert res.data and res.data["_ns_"]
    assert res.partial is True
    assert any("shard dropped" in w for w in res.stats.warnings)


def test_metadata_http_payload_flags_partial(cluster):
    """GET /api/v1/label/<name>/values with partial_response=true and a
    dead node: 200 with the survivors' values, plus the partial flag +
    warnings in the payload (the per-request param must reach the
    metadata path)."""
    from filodb_tpu.http.routes import PromHttpApi
    c, _ = cluster
    api = PromHttpApi({"prometheus": c.engine})
    c.servers["nodeB"].stop()
    # without the opt-in: hard 400 with the typed error
    status, payload = api.handle(
        "GET", "/api/v1/label/_ns_/values", {})
    assert status == 400
    assert payload["error"].startswith("shard_unavailable")
    # with it: flagged partial from the survivors
    status, payload = api.handle(
        "GET", "/api/v1/label/_ns_/values", {"partial_response": "true"})
    assert status == 200, payload
    assert payload["data"]
    assert payload["partial"] is True
    assert payload["warnings"]


# ----------------------------------------------------- cache exclusion


def test_result_cache_never_stores_partials():
    from filodb_tpu.query.rangevector import QueryResult, QueryStats
    from filodb_tpu.query.resultcache import ResultCache

    cache = ResultCache()
    calls = []

    def run_partial(s0, e0):
        calls.append((s0, e0))
        r = QueryResult([], QueryStats())
        r.partial = True
        r.stats.partial = True
        return r

    state = (((1, 1, 0),), 10 ** 15)    # (token, horizon_ms): cacheable
    res = cache.query_range(run_partial, "up", 1000, 10, 1300, "pp", state)
    assert res.partial is True
    assert len(cache) == 0              # never stored
    # a re-poll runs again — there is no poisoned entry to serve
    cache.query_range(run_partial, "up", 1000, 10, 1300, "pp", state)
    assert len(calls) == 2 and len(cache) == 0


@pytest.mark.chaos
def test_chaos_sigkill_gates():
    """The ISSUE-11 acceptance run (gate FLIPPED from the PR-4 stance):
    SIGKILL one of three RF-2 data nodes mid ingest+query traffic.
    Queries stay FULL through the kill via replica failover
    (availability 1.0 with ZERO partials — the partial path engages
    only when every owner of a shard is dead), no acked slab is lost
    (the surviving owner held it; WAL-segment catch-up repairs the
    respawn), and no result ever claims to be full while missing a
    shard's group.  Excluded from tier-1 (chaos implies slow); also
    runnable standalone: `python bench.py chaos`."""
    import json as _json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "chaos",
         "--quick"],
        capture_output=True, text=True, timeout=600, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    r = _json.loads(line)
    assert r["chaos_queries"]["fault"] > 0
    assert r["chaos_availability"] == 1.0, r
    assert r["chaos_partial_rate"] == 0.0, r
    assert r["chaos_acked_lost"] == 0, r
    assert r["chaos_wrong_full_results"] == 0, r
    assert r["chaos_p99_during_fault_s"] <= 2 * r["healthy_p99_s"], r
    # the respawned node was repaired through WAL-segment catch-up and
    # full results kept flowing
    assert r["chaos_recovered_full_results"] > 0, r


def test_result_cache_partial_tail_drops_entry_and_reruns():
    """A cached healthy prefix whose TAIL run comes back partial must not
    merge: the entry drops and the poll is served by one full run."""
    from filodb_tpu.ops.timewindow import make_window_ends
    from filodb_tpu.query.rangevector import (QueryResult, QueryStats,
                                              RangeVectorKey, ResultBlock)
    from filodb_tpu.query.resultcache import ResultCache

    cache = ResultCache()
    key = RangeVectorKey.make({"inst": "a"})
    partial_mode = {"on": False}
    full_runs = []

    def run(s0, e0):
        wends = make_window_ends(s0 * 1000, e0 * 1000, 10_000)
        r = QueryResult([ResultBlock([key], wends,
                                     np.ones((1, wends.size)))],
                        QueryStats())
        if partial_mode["on"]:
            r.partial = True
            r.stats.partial = True
        else:
            full_runs.append((s0, e0))
        return r

    state = (((1, 1, 0),), 1_200_000)   # horizon: windows <= 1200s final
    r1 = cache.query_range(run, "up", 1000, 10, 1200, "pp", state)
    assert r1.partial is False and len(cache) == 1
    # now the tail degrades: shards died — the poll must return ONE full
    # (partial-flagged) run and the poisoned-merge entry must be gone
    partial_mode["on"] = True
    r2 = cache.query_range(run, "up", 1000, 10, 1290, "pp", state)
    assert len(cache) == 0
    assert r2.partial is True
