"""Live query introspection (PR 13): the active-query registry,
cooperative cancellation races, kill propagation, and the crash log.

The race matrix the ISSUE names explicitly:
  * kill during queue wait — the slot is never held
  * kill between exec nodes — the next node never runs
  * kill of a singleflight leader — waiting followers re-execute
  * remote kill frame vs. an already-completed child — idempotent no-op
  * double-kill — second kill reports killed=False, counter moves once
"""
import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.query.activequeries import (ActiveQueryRegistry,
                                            active_queries,
                                            bind_client_conn, verdict_of)
from filodb_tpu.query.execbase import (ExecPlan, LeafExecPlan,
                                       NonLeafExecPlan, QueryError)
from filodb_tpu.query.frontend import QueryFrontend
from filodb_tpu.query.rangevector import (PlannerParams, QueryContext,
                                          QueryResult, QueryStats)
from filodb_tpu.utils.metrics import registry


def _drain_registry():
    """Tests must not leak entries into each other (the registry is
    process-wide, like the metrics registry)."""
    for ent in active_queries.entries():
        active_queries.deregister(ent, "error")


@pytest.fixture(autouse=True)
def _clean_registry():
    _drain_registry()
    yield
    _drain_registry()


class _FakeEngine:
    """Engine stand-in: blocks until released or its query's token is
    cancelled (polling — the cooperative contract), counting calls."""

    def __init__(self, block: bool = False):
        self.dataset = "ds"
        self.block = block
        self.release = threading.Event()
        self.calls = 0
        self.lock = threading.Lock()

    def query_range(self, promql, s, st, e, pp=None):
        from filodb_tpu.query.activequeries import take_admission
        ent = take_admission()           # the real engine pops it too
        with self.lock:
            self.calls += 1
        if self.block:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if self.release.is_set():
                    break
                if ent is not None and ent.token.cancelled:
                    return QueryResult(
                        [], error="query_canceled: killed mid-execution")
                time.sleep(0.01)
        res = QueryResult([])
        res.trace_id = ent.query_id if ent is not None else ""
        return res


def _frontend(engine, max_concurrent=0, singleflight=True):
    cfg = FilodbSettings()
    cfg.query.max_concurrent_queries = max_concurrent
    cfg.query.singleflight_enabled = singleflight
    cfg.query.tenant_usage_enabled = False
    cfg.query.result_cache_enabled = False
    return QueryFrontend(engine, config=cfg)


# ------------------------------------------------------------ registry


def test_register_kill_deregister_and_gauges():
    ent = active_queries.register("q1", promql="up", tenant=("acme", "ns"),
                                  origin="query_range")
    assert ent.phase == "queued"
    active_queries.refresh_gauges()      # gauges publish at scrape time
    assert registry.gauge("queries_inflight", ws="acme").value == 1
    assert registry.gauge("query_queue_depth", ws="acme").value == 1
    ent.set_phase("executing")
    active_queries.refresh_gauges()
    assert registry.gauge("query_queue_depth", ws="acme").value == 0
    before = registry.counter("queries_killed", reason="admin").value
    out = active_queries.kill("q1")
    assert out["killed"] is True
    assert ent.token.cancelled and ent.token.reason == "admin"
    assert registry.counter("queries_killed",
                            reason="admin").value == before + 1
    active_queries.deregister(ent, "killed")
    active_queries.refresh_gauges()
    assert registry.gauge("queries_inflight", ws="acme").value == 0
    assert active_queries.get("q1") == []


def test_double_kill_is_idempotent():
    ent = active_queries.register("q2", promql="up")
    before = registry.counter("queries_killed", reason="admin").value
    assert active_queries.kill("q2")["killed"] is True
    assert active_queries.kill("q2")["killed"] is False
    assert registry.counter("queries_killed",
                            reason="admin").value == before + 1
    active_queries.deregister(ent, "killed")
    # a kill AFTER completion: unknown id, nothing happens
    assert active_queries.kill("q2")["killed"] is False


def test_double_deregister_is_a_noop():
    # the sole entry under its id: the second deregister must not
    # decrement the tenant's inflight count again
    ent = active_queries.register("qdd", promql="up", tenant=("dd", ""))
    other = active_queries.register("qdd2", promql="up", tenant=("dd", ""))
    active_queries.deregister(ent, "completed")
    active_queries.deregister(ent, "completed")
    active_queries.refresh_gauges()
    assert registry.gauge("queries_inflight", ws="dd").value == 1
    active_queries.deregister(other, "completed")


def test_disabled_registry_returns_none_entries():
    reg = ActiveQueryRegistry()
    reg.configure(enabled=False)
    assert reg.register("qx", promql="up") is None
    reg.deregister(None)                 # no-op, no crash
    assert reg.kill("qx")["killed"] is False


def test_verdict_of():
    assert verdict_of(QueryResult([])) == "completed"
    assert verdict_of(QueryResult([], error="query_canceled: x")) == "killed"
    assert verdict_of(QueryResult([], error="query_timeout: x")) == "deadline"
    assert verdict_of(QueryResult([], error="boom")) == "error"
    assert verdict_of(None) == "completed"


# ----------------------------------------------------- race: queue wait


def test_kill_during_queue_wait_never_holds_slot():
    eng = _FakeEngine(block=True)
    fe = _frontend(eng, max_concurrent=1, singleflight=False)
    pp = PlannerParams()
    results = {}

    def client(name, promql):
        results[name] = fe.query_range(promql, 0, 15, 600, pp)

    t1 = threading.Thread(target=client, args=("a", "up"))
    t1.start()
    # wait until A holds the slot (inside the blocking engine)
    deadline = time.monotonic() + 2.0
    while eng.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert eng.calls == 1
    t2 = threading.Thread(target=client, args=("b", "up{x=\"1\"}"))
    t2.start()
    # B is queued: find its entry and kill it
    ent_b = None
    deadline = time.monotonic() + 2.0
    while ent_b is None and time.monotonic() < deadline:
        for e in active_queries.entries():
            if e.promql == 'up{x="1"}' and e.phase == "queued":
                ent_b = e
        time.sleep(0.01)
    assert ent_b is not None
    active_queries.kill(ent_b.query_id)
    t2.join(timeout=3)
    assert not t2.is_alive()
    assert results["b"].error.startswith("query_canceled")
    # the killed query never held (or has released) the slot: a third
    # query admits as soon as A releases, with no queue-timeout path
    eng.release.set()
    t1.join(timeout=3)
    assert results["a"].error is None
    eng.block = False
    t0 = time.monotonic()
    res_c = fe.query_range("up_c", 0, 15, 600, pp)
    assert res_c.error is None
    assert time.monotonic() - t0 < 1.0
    # the engine ran A and C, never B
    assert eng.calls == 2


# ------------------------------------------ race: between exec nodes


class _SleepLeaf(LeafExecPlan):
    ran = 0

    def _do_execute(self, source):
        type(self).ran += 1
        return None, QueryStats()


class _KillingLeaf(LeafExecPlan):
    """Simulates the kill landing while this node executes."""

    def _do_execute(self, source):
        self.ctx.cancel.cancel("admin", "test kill between nodes")
        return None, QueryStats()


class _Concat(NonLeafExecPlan):
    def compose(self, results, stats):
        return None


def test_kill_between_exec_nodes_stops_the_tree():
    from filodb_tpu.query.activequeries import CancellationToken
    ctx = QueryContext(query_id="qtree")
    ctx.cancel = CancellationToken()
    _SleepLeaf.ran = 0
    root = _Concat(ctx, [_KillingLeaf(ctx), _SleepLeaf(ctx),
                         _SleepLeaf(ctx)])
    res = root.execute(None)
    assert res.error is not None and res.error.startswith("query_canceled")
    # the boundary check stopped the scatter: later leaves never ran
    assert _SleepLeaf.ran == 0


def test_paging_loop_honors_cancel():
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store import (InMemoryColumnStore,
                                       InMemoryMetaStore)
    from filodb_tpu.ingest.generator import batch_stream, gauge_batch
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard = ms.setup("prometheus", 0)
    batch = gauge_batch(8, 40)
    for b, off in batch_stream(batch, samples_per_chunk=10):
        shard.ingest(b, off)
    shard.flush_all_groups()
    # fresh node: data only on the column store — a query must page
    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard2 = ms2.setup("prometheus", 0)
    shard2.recover_index()
    pids = np.arange(shard2.num_partitions, dtype=np.int64)

    calls = []

    def cancel():
        calls.append(1)
        if len(calls) >= 2:
            raise QueryError("query_canceled", "killed during paging")

    with pytest.raises(QueryError, match="query_canceled"):
        shard2.ensure_paged_pids("gauge", pids, 0, 10_000_000,
                                 cancel=cancel)
    assert len(calls) >= 2


# ----------------------------------------- race: singleflight leader


def test_singleflight_leader_killed_followers_reexecute():
    eng = _FakeEngine(block=True)
    fe = _frontend(eng, max_concurrent=0, singleflight=True)
    pp = PlannerParams()
    results = {}

    def client(name):
        results[name] = fe.query_range("up", 0, 15, 600, pp)

    t_leader = threading.Thread(target=client, args=("leader",))
    t_leader.start()
    deadline = time.monotonic() + 2.0
    while eng.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    t_follower = threading.Thread(target=client, args=("follower",))
    t_follower.start()
    # only the LEADER registers (followers ride its flight holding
    # nothing); give the follower a moment to park on the dedup wait,
    # then kill the leader
    deadline = time.monotonic() + 2.0
    leader_ent = None
    while time.monotonic() < deadline:
        ents = [e for e in active_queries.entries() if e.promql == "up"]
        if ents:
            leader_ent = ents[0]
            break
        time.sleep(0.01)
    time.sleep(0.1)
    assert leader_ent is not None
    assert len([e for e in active_queries.entries()
                if e.promql == "up"]) == 1
    # follower must NOT block the engine again: release lets any
    # re-execution return instantly
    eng.block = False
    active_queries.kill(leader_ent.query_id)
    t_leader.join(timeout=3)
    t_follower.join(timeout=3)
    assert results["leader"].error.startswith("query_canceled")
    # the follower saw the leader's cancellation and re-executed solo
    assert results["follower"].error is None
    assert eng.calls == 2


# ------------------------------------- remote kill frames (transport)


def test_remote_kill_frame_and_already_completed_child():
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.transport import NodeQueryServer, send_kill
    srv = NodeQueryServer(TimeSeriesMemStore()).start()
    host, port = srv.address
    try:
        # a live "remote execution" on this node: the kill frame finds
        # its token by query id
        ent = active_queries.register("rq1", promql="[remote] leaf",
                                      origin="remote", role="remote")
        out = send_kill(host, port, "rq1")
        assert out["killed"] is True
        assert ent.token.cancelled
        active_queries.deregister(ent, "killed")
        # already-completed (or never-seen) child: idempotent no-op
        out = send_kill(host, port, "rq1")
        assert out["killed"] is False
        out = send_kill(host, port, "never-existed")
        assert out["killed"] is False
    finally:
        srv.stop()


def test_remote_execution_registers_and_kill_mid_dispatch():
    """A dispatched subtree registers under the coordinator's query id
    on the remote node, and a kill frame arriving mid-execution stops
    the scan: the coordinator gets the structured query_canceled."""
    from filodb_tpu.parallel.testcluster import make_two_node_cluster
    from filodb_tpu.ingest.generator import gauge_batch
    cluster = make_two_node_cluster([gauge_batch(64, 60)], num_shards=4)
    try:
        qid_seen = []
        orig_register = active_queries.register

        def spy_register(qid, **kw):
            if kw.get("role") == "remote":
                qid_seen.append(qid)
            return orig_register(qid, **kw)

        s0 = 1_600_000_000
        active_queries.register = spy_register
        try:
            res = cluster.engine.query_range("sum(heap_usage)",
                                             s0 + 120, 15, s0 + 590)
        finally:
            active_queries.register = orig_register
        assert res.error is None
        # every remote dispatch registered under ONE query id
        assert qid_seen and all(q == qid_seen[0] for q in qid_seen)
    finally:
        cluster.stop()


# ---------------------------------------------- disconnect detection


def test_client_disconnect_trips_token():
    a, b = socket.socketpair()
    try:
        active_queries.watch_interval_s = 0.02
        with bind_client_conn(b):
            ent = active_queries.register("qdisc", promql="up",
                                          tenant=("t", ""))
        assert ent.client_conn is b
        a.close()                        # the client hangs up mid-query
        deadline = time.monotonic() + 3.0
        while not ent.token.cancelled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ent.token.cancelled
        assert ent.token.reason == "disconnect"
        active_queries.deregister(ent, "killed")
    finally:
        active_queries.watch_interval_s = 0.1
        b.close()


# ------------------------------------------------- crash-durable file


def test_crash_log_replay(tmp_path):
    from filodb_tpu.utils.events import journal
    path = str(tmp_path / "queries.active")
    reg = ActiveQueryRegistry()
    reg.configure(path=path)
    done = reg.register("done1", promql="up", tenant=("t", ""))
    reg.deregister(done, "completed")
    reg.register("crashed1", promql="sum(rate(x[30d]))", tenant=("t", ""))
    # "crash": a fresh process replays the file
    reg2 = ActiveQueryRegistry()
    reg2.configure(path=path)
    seq0 = journal.next_seq
    assert reg2.replay_crash_log() == 1
    evs = [e for e in journal.since(seq0 - 1)
           if e["kind"] == "query_active_at_crash"]
    assert len(evs) == 1
    assert evs[0]["query_id"] == "crashed1"
    # file truncated: a second replay finds nothing
    assert reg2.replay_crash_log() == 0


# ------------------------------------------------------- HTTP routes


def _api():
    from filodb_tpu.http.routes import PromHttpApi
    cfg = FilodbSettings()
    cfg.query.tenant_usage_enabled = False
    return PromHttpApi({}, config=cfg)


def test_admin_queries_routes():
    api = _api()
    st, payload = api.handle("GET", "/admin/queries", {})
    assert st == 200 and payload["data"]["count"] == 0
    ent = active_queries.register("qhttp", promql="sum(up)",
                                  tenant=("acme", "ns"), origin="query")
    ent.set_phase("executing")
    ent.add(samples=123, paged_bytes=456, dispatches=2)
    ent.note_remote("127.0.0.1:9999")
    st, payload = api.handle("GET", "/admin/queries", {})
    assert st == 200
    rows = payload["data"]["queries"]
    assert len(rows) == 1
    q = rows[0]
    assert q["queryID"] == "qhttp" and q["phase"] == "executing"
    assert q["counters"]["samplesScanned"] == 123
    assert q["counters"]["bytesPaged"] == 456
    assert q["remoteNodes"] == ["127.0.0.1:9999"]
    # tenant filter
    st, payload = api.handle("GET", "/admin/queries", {"tenant": "other"})
    assert payload["data"]["count"] == 0
    # detail + kill (propagation to the dead 9999 child is counted, not
    # fatal)
    st, payload = api.handle("GET", "/admin/queries/qhttp", {})
    assert st == 200
    st, payload = api.handle("POST", "/admin/queries/qhttp/kill", {})
    assert st == 200 and payload["data"]["killed"] is True
    assert payload["data"]["propagationErrors"] == 1
    assert ent.token.cancelled
    active_queries.deregister(ent, "killed")
    # unknown id: 404, not an error
    st, payload = api.handle("POST", "/admin/queries/qhttp/kill", {})
    assert st == 404
    # bad reason: 400
    ent2 = active_queries.register("q2http", promql="up")
    st, payload = api.handle("POST", "/admin/queries/q2http/kill",
                             {"reason": "zap"})
    assert st == 400
    active_queries.deregister(ent2, "completed")


def test_trace_verdict_and_slowlog_crosslink():
    from filodb_tpu.utils.metrics import collector
    from filodb_tpu.utils.slowlog import slowlog
    api = _api()
    tid = "croslnk1"
    collector.record(tid, {"span": "execplan", "end_unix_s": 1.0})
    collector.note_verdict(tid, "killed")
    res = QueryResult([], error="query_canceled: killed")
    res.trace_id = tid
    slowlog.maybe_record("sum(up)", 0, 15, 600, 99.0, res,
                         tenant=("t", ""), threshold_s=1.0)
    st, payload = api.handle("GET", f"/admin/traces/{tid}", {})
    assert st == 200
    data = payload["data"]
    assert data["verdict"] == "killed"
    assert data["queryID"] == tid
    assert isinstance(data.get("slowlogSeq"), int)
    # the slowlog entry cross-links back: query id + verdict ride it
    entry = [e for e in slowlog.entries() if e["trace_id"] == tid][-1]
    assert entry["query_id"] == tid
    assert entry["verdict"] == "killed"
    assert entry["seq"] == data["slowlogSeq"]


# ------------------------------------------- end-to-end kill via HTTP


def test_frontend_kill_mid_execution_structured_error():
    eng = _FakeEngine(block=True)
    fe = _frontend(eng)
    pp = PlannerParams()
    out = {}

    def client():
        out["res"] = fe.query_range("up", 0, 15, 600, pp)

    t = threading.Thread(target=client)
    t.start()
    deadline = time.monotonic() + 2.0
    ent = None
    while ent is None and time.monotonic() < deadline:
        ents = active_queries.entries()
        if ents:
            ent = ents[0]
        time.sleep(0.01)
    assert ent is not None
    active_queries.kill(ent.query_id, reason="admin")
    t.join(timeout=3)
    res = out["res"]
    assert res.error is not None and res.error.startswith("query_canceled")
    # verdict landed on the trace
    from filodb_tpu.utils.metrics import collector
    assert collector.verdict(res.trace_id) in ("killed", "")
    # registry is clean again
    assert active_queries.get(ent.query_id) == []
