"""PR 3 observability: per-query resource attribution (QueryStats phase
seconds merged bottom-up and over the wire), explain?analyze per-node
annotations, result-cache / device-mirror cache attribution, the
slow-query flight recorder, and per-tenant usage accounting + limits.
"""
import json
import time

import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.core.index import Equals
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
from filodb_tpu.parallel.transport import (NodeQueryServer,
                                           RemoteNodeDispatcher)
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.exec import (AggregateMapReduce, AnalyzeRecorder,
                                   DistConcatExec, MultiSchemaPartitionsExec,
                                   PeriodicSamplesMapper)
from filodb_tpu.query.frontend import QueryFrontend
from filodb_tpu.query.rangevector import QueryContext
from filodb_tpu.utils.slowlog import SlowQueryLog, slowlog
from filodb_tpu.utils.usage import UsageAccountant, tenant_of, usage

START = 1_600_000_000_000
S_SEC = START // 1000
Q = 'sum by (_ns_)(rate(request_total[5m]))'


def _slice(full, lo_i, hi_i):
    keep = ((full.timestamps >= START + lo_i * 10_000)
            & (full.timestamps < START + hi_i * 10_000))
    return RecordBatch(full.schema, full.part_keys, full.part_idx[keep],
                       full.timestamps[keep],
                       {k: v[keep] for k, v in full.columns.items()},
                       full.bucket_les)


@pytest.fixture()
def store2shard():
    ms = TimeSeriesMemStore()
    full = counter_batch(40, 300, start_ms=START)
    for s in (0, 1):
        ms.setup("prometheus", s)
    # route half the keys to each shard by part_idx parity
    even = full.part_idx % 2 == 0
    for s, mask in ((0, even), (1, ~even)):
        ms.get_shard("prometheus", s).ingest(RecordBatch(
            full.schema, full.part_keys, full.part_idx[mask],
            full.timestamps[mask],
            {k: v[mask] for k, v in full.columns.items()},
            full.bucket_les))
    mapper = ShardMapper(2)
    for s in (0, 1):
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", s, "local"))
    return ms, QueryEngine("prometheus", ms, mapper)


# ----------------------------------------------- stats totals vs the tree


def test_stats_totals_equal_sum_over_exec_nodes(store2shard):
    ms, eng = store2shard
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    plan = query_range_to_logical_plan(
        Q, TimeStepParams(S_SEC + 600, 60, S_SEC + 2800))
    ctx = QueryContext(query_id="analyze-1")
    ep = eng.planner.materialize(plan, ctx)
    rec = AnalyzeRecorder()
    ctx.analyze = rec
    res = ep.execute(ms)
    assert res.error is None, res.error
    st = res.stats
    # exclusive per-node seconds are additive: their sum IS the root cpu
    assert rec.order, "no nodes recorded"
    assert sum(n["self_s"] for n in rec.order) == pytest.approx(
        st.cpu_seconds, rel=1e-6)
    assert sum(n["device_s"] for n in rec.order) == pytest.approx(
        st.device_seconds, rel=1e-6)
    # leaf scan counters sum to the root's (leaves report their own scan)
    leaves = [n for n in rec.order
              if n["plan"] == "MultiSchemaPartitionsExec"]
    assert len(leaves) == 2              # one per shard
    assert sum(n["samples_scanned"] for n in leaves) == st.samples_scanned
    assert sum(n["series_scanned"] for n in leaves) == st.series_scanned
    assert st.shards_queried == 2
    # annotated tree carries the attribution inline
    tree = ep.print_tree(annot=rec.annotation)
    assert "[self=" in tree and "samples=" in tree
    # the wire dict exposes the same totals
    d = st.to_dict()
    assert d["phases"]["exec_s"] == pytest.approx(st.cpu_seconds, abs=1e-6)
    assert d["samplesScanned"] == st.samples_scanned


def test_stats_reconcile_with_stitched_trace(store2shard):
    """The per-phase attribution must agree with the span tree: every
    exec node produced a span under the query's trace id, and the trace's
    execplan span durations bound the stats' exec seconds from above
    (span wall includes children; cpu_seconds is exclusive)."""
    ms, eng = store2shard
    from filodb_tpu.utils.metrics import collector
    res = eng.query_range(Q, S_SEC + 600, 60, S_SEC + 2800)
    assert res.error is None
    evs = collector.trace(res.trace_id)
    exec_spans = [e for e in evs if e["span"].startswith("execplan")]
    assert exec_spans, "exec nodes left no spans in the trace"
    root_wall = max(e["dur_s"] for e in exec_spans)
    assert res.stats.cpu_seconds <= root_wall + 0.05
    assert res.stats.cpu_seconds > 0


# ----------------------------------------------------- wire round-trip


def test_stats_survive_wire_roundtrip_two_nodes():
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(16, 240, start_ms=START))
    server = NodeQueryServer(ms).start()
    try:
        host, port = server.address
        ctx = QueryContext(query_id="wire-1")
        leaf = MultiSchemaPartitionsExec(
            ctx, "prometheus", 0, [Equals("_metric_", "request_total")],
            START, START + 3_600_000)
        leaf.add_transformer(PeriodicSamplesMapper(
            START + 600_000, 60_000, START + 2_400_000, 300_000, "rate", ()))
        leaf.add_transformer(AggregateMapReduce("sum", (), (), ()))
        leaf.dispatcher = RemoteNodeDispatcher(host, port, timeout_s=30)
        root = DistConcatExec(ctx, [leaf])
        res = root.execute(ms)
        assert res.error is None, res.error
        st = res.stats
        # the remote's exec attribution merged into the coordinator root
        assert st.samples_scanned > 0 and st.shards_queried == 1
        assert st.cpu_seconds > 0
        # wire bytes: request frame + reply frame counted
        assert st.bytes_transferred > 0
    finally:
        server.stop()


# ------------------------------------------------- cache attribution


def test_cold_vs_cached_repoll_cache_attribution():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    full = counter_batch(30, 360, start_ms=START)
    sh.ingest(_slice(full, 0, 240), offset=0)
    eng = QueryEngine("prometheus", ms)
    fe = QueryFrontend(eng)
    args = (S_SEC + 600, 60, S_SEC + 2390)
    cold = fe.query_range(Q, *args)
    assert cold.error is None
    assert cold.stats.result_cache == "miss"
    assert cold.stats.samples_scanned > 0
    warm = fe.query_range(Q, *args)
    assert warm.stats.result_cache == "hit"
    assert warm.stats.samples_scanned == 0      # nothing rescanned
    # live edge advances -> slid re-poll recomputes only the tail (the
    # device-mirror leaf gathers whole rows, so the scan COUNT can match
    # a full recompute's — the attribution verdict is what must differ)
    sh.ingest(_slice(full, 240, 360), offset=1)
    part = fe.query_range(Q, S_SEC + 720, 60, S_SEC + 3590)
    assert part.stats.result_cache == "partial"
    recompute = eng.query_range(Q, S_SEC + 720, 60, S_SEC + 3590)
    assert 0 < part.stats.samples_scanned \
        <= recompute.stats.samples_scanned
    # the tail recomputed fewer windows than the full range carries
    assert part.stats.result_samples == recompute.stats.result_samples


def test_mirror_rebuild_attribution():
    """The query that pays a device-mirror upload on its critical path
    says so in its stats; the warm repeat does not."""
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(16, 240, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    r1 = eng.query_range(Q, S_SEC + 600, 60, S_SEC + 2390)
    assert r1.error is None
    assert r1.stats.mirror_full_rebuilds >= 1
    assert r1.stats.bytes_transferred > 0
    r2 = eng.query_range(Q, S_SEC + 600, 60, S_SEC + 2390)
    assert r2.stats.mirror_full_rebuilds == 0
    assert r2.stats.mirror_incremental == 0


# ------------------------------------------------------------- slowlog


def test_slowlog_captures_slow_query_with_trace():
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(16, 240, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    cfg = FilodbSettings()
    cfg.query.slow_query_threshold_s = 1e-9     # everything is slow
    fe = QueryFrontend(eng, config=cfg)
    slowlog.clear()
    res = fe.query_range(Q, S_SEC + 600, 60, S_SEC + 2390)
    assert res.error is None
    entries = slowlog.entries()
    assert entries, "slow query was not recorded"
    rec = entries[-1]
    assert rec["promql"] == Q
    assert rec["duration_s"] > 0
    assert rec["trace_id"] == res.trace_id
    assert rec["stats"]["phases"]["exec_s"] > 0
    # the stitched span tree rode along (copied at record time)
    assert any(e["span"].startswith("execplan") for e in rec["spans"])
    json.dumps(rec)                             # JSONL-sink serializable
    slowlog.clear()


def test_slowlog_jsonl_sink_and_threshold(tmp_path):
    sl = SlowQueryLog(threshold_s=10.0, max_entries=4,
                      path=str(tmp_path / "slow.jsonl"))

    class _Res:
        trace_id = ""
        error = None
        partial = False
        stats = None

    assert not sl.maybe_record("q", 0, 60, 100, 0.5, _Res())   # under
    assert sl.maybe_record("q", 0, 60, 100, 11.0, _Res())      # over
    assert len(sl) == 1
    lines = (tmp_path / "slow.jsonl").read_text().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["promql"] == "q"
    # ring bound holds
    for i in range(10):
        sl.maybe_record(f"q{i}", 0, 60, 100, 12.0, _Res())
    assert len(sl) == 4


# ------------------------------------------------------- tenant usage


def test_tenant_usage_accounting_ingest_and_query():
    usage.clear()
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(20, 120, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    fe = QueryFrontend(eng)
    q = 'sum(rate(request_total{_ws_="demo",_ns_="App-0"}[5m]))'
    assert tenant_of(q) == ("demo", "App-0")
    res = fe.query_range(q, S_SEC + 600, 60, S_SEC + 1190)
    assert res.error is None
    rows = {(r["ws"], r["ns"]): r for r in usage.snapshot()}
    # ingest attributed per tenant (generator tags _ws_=demo, _ns_=App-N)
    assert rows[("demo", "App-0")]["ingestSamples"] > 0
    # the query charged to its shard-key tenant
    assert rows[("demo", "App-0")]["queries"] == 1
    assert rows[("demo", "App-0")]["samplesScanned"] > 0
    assert rows[("demo", "App-0")]["querySeconds"] > 0


def test_tenant_fail_limit_rejects_with_structured_error():
    usage.clear()
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(20, 120, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    cfg = FilodbSettings()
    cfg.query.tenant_samples_warn_limit = 1
    cfg.query.tenant_samples_fail_limit = 10
    fe = QueryFrontend(eng, config=cfg)
    q = 'sum(rate(request_total{_ws_="demo",_ns_="App-1"}[5m]))'
    first = fe.query_range(q, S_SEC + 600, 60, S_SEC + 1190)
    assert first.error is None           # the crossing query still runs
    assert first.stats.samples_scanned > 10
    second = fe.query_range(q, S_SEC + 600, 60, S_SEC + 1190)
    assert second.error is not None
    assert second.error.split(":", 1)[0] == "tenant_limit_exceeded"
    # window roll re-admits
    usage.clear()
    third = fe.query_range(q, S_SEC + 600, 60, S_SEC + 1190)
    assert third.error is None


def test_singleflight_followers_do_not_multiply_usage():
    """Dedup'd followers ride the leader's execution: the tenant must be
    billed once per EXECUTION, not once per client, and the slowlog must
    not record N identical entries for one shared run."""
    import threading

    from filodb_tpu.query.rangevector import QueryResult

    usage.clear()
    slowlog.clear()
    calls = [0]
    lock = threading.Lock()

    class StubEngine:
        dataset = "d"
        source = None                    # no shard state -> cache bypass

        def query_range(self, q, s, st, e, pp=None):
            with lock:
                calls[0] += 1
            time.sleep(0.15)
            return QueryResult([])

    cfg = FilodbSettings()
    cfg.query.slow_query_threshold_s = 1e-9
    fe = QueryFrontend(StubEngine(), config=cfg)
    barrier = threading.Barrier(8)

    def client():
        barrier.wait()
        fe.query_range('m{_ws_="sfw"}', 1, 60, 100)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    rows = {(r["ws"], r["ns"]): r for r in usage.snapshot()}
    assert rows[("sfw", "")]["queries"] == calls[0] < 8
    assert len(slowlog.entries()) == calls[0]
    usage.clear()
    slowlog.clear()


def test_explain_analyze_respects_tenant_limits_and_accounting():
    """analyze_range goes through the same admission + accounting as
    query_range: it must not be a free pass around the tenant limits."""
    usage.clear()
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(20, 120, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    cfg = FilodbSettings()
    cfg.query.tenant_samples_fail_limit = 10
    fe = QueryFrontend(eng, config=cfg)
    q = 'sum(rate(request_total{_ws_="demo",_ns_="App-2"}[5m]))'
    res, rec, ep = fe.analyze_range(q, S_SEC + 600, 60, S_SEC + 1190)
    assert res.error is None and rec is not None and rec.order
    rows = {(r["ws"], r["ns"]): r for r in usage.snapshot()}
    assert rows[("demo", "App-2")]["queries"] == 1       # analyze billed
    assert rows[("demo", "App-2")]["samplesScanned"] > 10
    res2, rec2, _ = fe.analyze_range(q, S_SEC + 600, 60, S_SEC + 1190)
    assert rec2 is None
    assert res2.error.startswith("tenant_limit_exceeded")
    usage.clear()


def test_usage_tenant_cardinality_bounded():
    """Query text is client-controlled: distinct (_ws_, _ns_) pairs past
    the cap must fold into the overflow tenant, not grow the accountant
    (and the registry's tenant-tagged counters) without bound."""
    from filodb_tpu.utils.usage import OVERFLOW_TENANT
    acc = UsageAccountant()
    for i in range(acc.MAX_TENANTS + 50):
        acc.record_query(f"ws{i}", "n", 0.001, 10, 1)
    rows = {(r["ws"], r["ns"]): r for r in acc.snapshot()}
    assert len(rows) <= acc.MAX_TENANTS + 1
    assert rows[OVERFLOW_TENANT]["queries"] >= 50
    # known tenants keep accounting under their own key
    acc.record_query("ws0", "n", 0.001, 10, 1)
    assert rows is not None and acc.resolve("ws0", "n") == ("ws0", "n")
    assert acc.resolve("brand-new", "n") == OVERFLOW_TENANT


def test_usage_window_rolls():
    acc = UsageAccountant(window_s=0.05)
    acc.record_query("w", "n", 0.1, 100, 10)
    assert acc.window_samples("w", "n") == 100
    time.sleep(0.06)
    assert acc.window_samples("w", "n") == 0
    assert acc.admit("w", "n", 0, 50) is None
    acc.record_query("w", "n", 0.1, 100, 10)
    err = acc.admit("w", "n", 0, 50)
    assert err and err.startswith("tenant_limit_exceeded")


# ----------------------------------------------------------- HTTP edges


def test_http_stats_explain_usage_slowlog_routes():
    from filodb_tpu.http.routes import PromHttpApi
    usage.clear()
    slowlog.clear()
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(16, 240, start_ms=START))
    eng = QueryEngine("prometheus", ms)
    api = PromHttpApi({"prometheus": eng})
    params = {"query": Q, "start": str(S_SEC + 600), "end": str(S_SEC + 2390),
              "step": "60", "stats": "true"}
    status, payload = api.handle("GET", "/api/v1/query_range", params)
    assert status == 200, payload
    st = payload["stats"]
    assert st["phases"]["exec_s"] > 0
    assert st["samplesScanned"] > 0
    assert st["cache"]["result"] in ("miss", "hit", "partial", "")
    # instant query stats
    status, payload = api.handle(
        "GET", "/api/v1/query",
        {"query": "request_total", "time": str(S_SEC + 1200),
         "stats": "all"})
    assert status == 200 and payload["stats"]["samplesScanned"] > 0
    # explain analyze: annotated tree + per-node records
    status, payload = api.handle(
        "GET", "/api/v1/explain",
        {"query": Q, "start": str(S_SEC + 600), "end": str(S_SEC + 2390),
         "step": "60", "analyze": "true"})
    assert status == 200, payload
    data = payload["data"]
    assert data["resultType"] == "execPlanAnalysis"
    assert any("[self=" in line for line in data["result"])
    assert data["nodes"] and data["stats"]["phases"]["exec_s"] > 0
    # plain explain still works
    status, payload = api.handle(
        "GET", "/api/v1/explain",
        {"query": Q, "start": str(S_SEC + 600), "end": str(S_SEC + 2390),
         "step": "60"})
    assert status == 200
    assert payload["data"]["resultType"] == "execPlan"
    # usage endpoint
    status, payload = api.handle("GET", "/api/v1/usage", {})
    assert status == 200 and isinstance(payload["data"], list)
    # slowlog endpoints
    status, payload = api.handle("GET", "/admin/slowlog", {})
    assert status == 200 and "entries" in payload["data"]
    status, payload = api.handle("POST", "/admin/slowlog/clear", {})
    assert status == 200


def test_profiler_collapsed_format_route():
    import threading

    from filodb_tpu.http.routes import PromHttpApi
    api = PromHttpApi({})
    stop = threading.Event()

    def hot_spin():
        x = 0
        while not stop.is_set():
            for i in range(2000):
                x += i * i
        return x

    t = threading.Thread(target=hot_spin, daemon=True)
    t.start()
    status, _ = api.handle("POST", "/admin/profiler/start", {"hz": "200"})
    assert status == 200
    time.sleep(0.4)
    status, rep = api.handle("GET", "/admin/profiler/report",
                             {"format": "collapsed"})
    assert status == 200
    stop.set(); t.join(timeout=5)
    api.handle("POST", "/admin/profiler/stop", {})
    lines = [ln for ln in rep.splitlines() if ln]
    assert lines, "no collapsed stacks"
    # every line: `frame;frame;... count` with the count numeric
    for ln in lines:
        frames, _, count = ln.rpartition(" ")
        assert frames and count.isdigit(), ln
    assert any("hot_spin" in ln for ln in lines)
    # unknown format rejected
    status, _ = api.handle("GET", "/admin/profiler/report",
                           {"format": "bogus"})
    assert status == 400
