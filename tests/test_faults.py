"""Fault-injection layer (utils/faults.py): arming, determinism, seeded
plans, and the production fault points actually firing where they claim
to.  The chaos bench (`python bench.py chaos`) is the macro counterpart;
these are the fast deterministic guarantees the tier-1 gate holds."""
import json
import socket
import time

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.utils.faults import (FaultRegistry, InjectedFault, faults)

START = 1_600_000_020_000


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------------------ registry unit


def test_unknown_point_and_kind_rejected():
    r = FaultRegistry(env={})
    with pytest.raises(ValueError, match="unknown fault point"):
        r.arm("no.such.point", "error")
    with pytest.raises(ValueError, match="unknown fault kind"):
        r.arm("ingest.batch", "explode")


def test_first_k_fires_exactly_first_k_calls():
    r = FaultRegistry(env={})
    r.arm("ingest.batch", "error", first_k=3)
    fired = 0
    for _ in range(10):
        try:
            r.fire("ingest.batch")
        except InjectedFault:
            fired += 1
    assert fired == 3
    snap = r.snapshot()[0]
    assert snap["calls"] == 10 and snap["fired"] == 3


def test_probability_schedule_is_seed_deterministic():
    def sequence(seed):
        r = FaultRegistry(env={})
        r.arm("ingest.batch", "error", probability=0.3, seed=seed)
        out = []
        for _ in range(200):
            try:
                r.fire("ingest.batch")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    a, b = sequence(7), sequence(7)
    assert a == b                       # same seed -> same schedule
    assert any(a) and not all(a)        # p=0.3 over 200 calls: mixed
    assert sequence(8) != a             # a different seed moves it


def test_kinds_error_drop_delay_corrupt():
    r = FaultRegistry(env={})
    r.arm("transport.send", "error", first_k=1, message="boom")
    with pytest.raises(InjectedFault, match="boom"):
        r.fire("transport.send")

    r.arm("transport.send", "drop", first_k=1)
    with pytest.raises(socket.timeout):
        r.fire("transport.send")

    r.arm("transport.send", "delay", first_k=1, delay_s=0.05)
    t0 = time.perf_counter()
    assert r.fire("transport.send", b"abc") == b"abc"
    assert time.perf_counter() - t0 >= 0.045

    r.arm("transport.recv", "corrupt", first_k=1, seed=3)
    payload = bytes(range(64))
    out = r.fire("transport.recv", payload)
    assert out != payload and len(out) == len(payload)
    # deterministic: the same seed corrupts the same positions
    r2 = FaultRegistry(env={})
    r2.arm("transport.recv", "corrupt", first_k=1, seed=3)
    assert r2.fire("transport.recv", payload) == out


def test_disabled_fast_path_passthrough():
    r = FaultRegistry(env={})
    assert r.fire("transport.send", b"x") == b"x"
    # armed on a DIFFERENT point: untouched too
    r.arm("ingest.batch", "error", first_k=1)
    assert r.fire("transport.send", b"x") == b"x"


def test_env_arming():
    spec = json.dumps([{"point": "flush.persist", "kind": "error",
                        "first_k": 2}])
    r = FaultRegistry(env={"FILODB_TPU_FAULTS": spec})
    with pytest.raises(InjectedFault):
        r.fire("flush.persist")


def test_plan_context_manager_disarms_on_exit():
    r_before = faults.snapshot()
    assert r_before == []
    with faults.plan("ingest.batch", "error", first_k=1):
        assert len(faults.snapshot()) == 1
    assert faults.snapshot() == []


# ------------------------------------------------- production fault points


def test_ingest_batch_point_fires_in_shard():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    batch = counter_batch(4, 10, start_ms=START)
    with faults.plan("ingest.batch", "error", first_k=1):
        with pytest.raises(InjectedFault):
            sh.ingest(batch)
        assert sh.ingest(batch) > 0     # first_k exhausted: recovers


def test_flush_persist_point_fires_in_flush():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(4, 50, start_ms=START))
    groups = {sh.partitions[p].group for p in range(sh.num_partitions)}
    with faults.plan("flush.persist", "error", first_k=100):
        with pytest.raises(InjectedFault):
            for g in sorted(groups):
                sh.flush_group(g)
    # disarmed: the same flush succeeds
    assert sum(sh.flush_group(g) for g in sorted(groups)) >= 0


def test_transport_points_fire_on_dispatch_path():
    from filodb_tpu.core.index import Equals
    from filodb_tpu.parallel.breaker import breakers
    from filodb_tpu.parallel.transport import (NodeQueryServer,
                                               RemoteNodeDispatcher)
    from filodb_tpu.query.exec import (AggregateMapReduce,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper)
    from filodb_tpu.query.execbase import QueryError
    from filodb_tpu.query.rangevector import QueryContext

    breakers.reset()
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    srv = NodeQueryServer(ms).start()
    try:
        disp = RemoteNodeDispatcher(*srv.address, timeout_s=10.0)

        def mk_plan():
            plan = MultiSchemaPartitionsExec(
                QueryContext(query_id="qf"), "prometheus", 0,
                [Equals("_metric_", "request_total")],
                START, START + 3_600_000)
            plan.add_transformer(PeriodicSamplesMapper(
                START + 600_000, 60_000, START + 3_600_000, 300_000,
                "rate", ()))
            plan.add_transformer(AggregateMapReduce("sum", (), (), ()))
            return plan

        # baseline: healthy dispatch
        data, stats = disp.dispatch(mk_plan(), None)
        assert stats.samples_scanned > 0

        # ONE send fault on a pooled socket: the stale-pool one-retry
        # path absorbs it (counted + visible), the dispatch succeeds
        from filodb_tpu.utils.metrics import registry
        retries0 = registry.counter("transport_stale_socket_retries").value
        with faults.plan("transport.send", "error", first_k=1):
            data1, stats1 = disp.dispatch(mk_plan(), None)
            assert stats1.samples_scanned > 0
        assert registry.counter(
            "transport_stale_socket_retries").value == retries0 + 1

        # TWO send faults: the retry fails too -> peer-death taxonomy
        with faults.plan("transport.send", "error", first_k=2):
            with pytest.raises(QueryError) as ei:
                disp.dispatch(mk_plan(), None)
            assert ei.value.code == "shard_unavailable"

        # corrupt reply -> loud remote_failure, never a mis-parse
        with faults.plan("transport.recv", "corrupt", first_k=1):
            with pytest.raises(QueryError) as ei:
                disp.dispatch(mk_plan(), None)
            assert ei.value.code == "remote_failure"
            # streamed replies report a per-frame CRC mismatch, legacy
            # single-frame replies a corrupt reply — both are the
            # typed remote_failure
            assert "corrupt" in str(ei.value)

        # dropped frame -> the timeout handling path, deterministically
        with faults.plan("transport.recv", "drop", first_k=1):
            with pytest.raises(QueryError) as ei:
                disp.dispatch(mk_plan(), None)
            assert ei.value.code == "dispatch_timeout"

        # after every fault the pooled connection recovers
        data2, stats2 = disp.dispatch(mk_plan(), None)
        assert stats2.samples_scanned == stats.samples_scanned
    finally:
        srv.stop()
        breakers.reset()


def test_flush_scheduler_backs_off_and_recovers():
    from filodb_tpu.core.flush import FlushScheduler
    from filodb_tpu.utils.metrics import registry

    ms = TimeSeriesMemStore()
    sh = ms.setup("chaos_flush", 0)
    sh.ingest(counter_batch(8, 80, start_ms=START))
    sched = FlushScheduler(ms, "chaos_flush", interval_s=0.5,
                           headroom=False)
    errs0 = registry.counter("flush_errors", dataset="chaos_flush",
                             shard="0").value
    try:
        with faults.plan("flush.persist", "error", first_k=10_000):
            sched.start()
            deadline = time.time() + 5.0
            while time.time() < deadline and not sched._backoff_until:
                time.sleep(0.02)
        # errors were counted per shard AND the shard entered backoff
        assert sched.errors > 0
        assert registry.counter("flush_errors", dataset="chaos_flush",
                                shard="0").value > errs0
        assert 0 in sched._backoff_until
        assert registry.gauge("flush_backoff_active",
                              dataset="chaos_flush").value == 1
        # disarmed: the next successful flush resets streak + gauge
        deadline = time.time() + 5.0
        while time.time() < deadline and sched._err_streak:
            time.sleep(0.02)
        assert not sched._err_streak
        assert registry.gauge("flush_backoff_active",
                              dataset="chaos_flush").value == 0
    finally:
        sched.stop(final_flush=False)
