"""Fuzz the pure-Python snappy block codec (utils/snappy.py).

Both the remote_write/remote-read doors AND the WAL's record framing
lean on this codec, so its two contracts get adversarial coverage:

  * round trip: compress→decompress is identity for random, RLE-heavy,
    and structured (real-payload-shaped) inputs;
  * robustness: decompress NEVER raises anything but ValueError and
    never hangs, for truncations, bit flips, and hand-built hostile
    copy-op streams — a malformed network payload must become a clean
    400 / WalCorruption, not an unhandled crash.
"""
import numpy as np
import pytest

from filodb_tpu.utils import snappy
from filodb_tpu.utils.varint import write_uvarint


# -------------------------------------------------------------- round trip

def test_roundtrip_random_payloads():
    rng = np.random.default_rng(11)
    for _ in range(40):
        n = int(rng.integers(0, 8000))
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert snappy.decompress(snappy.compress(data)) == data


def test_roundtrip_rle_heavy_payloads():
    """Long single-byte and short-period runs: the shapes that exercise
    overlapping (offset < length) copy ops on real decoders."""
    rng = np.random.default_rng(12)
    for period in (1, 2, 3, 4, 7, 64):
        for run in (4, 61, 200, 5000):
            base = bytes(rng.integers(0, 256, period,
                                      dtype=np.uint8).tobytes())
            data = (base * (run // period + 1))[:run] + b"tail"
            assert snappy.decompress(snappy.compress(data)) == data
    # alternating runs + noise (compressor must switch modes correctly)
    parts = []
    for i in range(50):
        parts.append(bytes([i % 251]) * int(rng.integers(1, 120)))
        parts.append(rng.integers(0, 256, int(rng.integers(0, 30)),
                                  dtype=np.uint8).tobytes())
    data = b"".join(parts)
    assert snappy.decompress(snappy.compress(data)) == data


def test_roundtrip_structured_payloads():
    """Real-client-shaped inputs: protobuf-ish label blocks with heavy
    shared prefixes and an f64 sample matrix — what a WriteRequest and a
    WAL record body actually look like."""
    rng = np.random.default_rng(13)
    labels = b"".join(
        b"\x0a\x08__name__\x12\x0ehttp_req_total"
        b"\x0a\x04_ws_\x12\x04demo\x0a\x08instance\x12\x06"
        + f"i-{i:04d}".encode() for i in range(200))
    floats = rng.normal(size=2048).astype("<f8").tobytes()
    ints = np.arange(4096, dtype="<i8").tobytes()
    for data in (labels, floats, ints, labels + floats + ints):
        out = snappy.decompress(snappy.compress(data))
        assert out == data
    # long period-8 payloads (zero padding, constant f64 lanes — the WAL
    # body shapes) must actually engage copy ops on the LARGE-payload
    # vectorized path, not degrade to all-literals
    rep = b"ABCDEFGH" * 16384                     # 128 KB, period 8
    assert snappy.decompress(snappy.compress(rep)) == rep
    assert len(snappy.compress(rep)) < len(rep) // 8
    zeros = np.zeros(40_000, dtype="<f8").tobytes()
    assert snappy.decompress(snappy.compress(zeros)) == zeros
    assert len(snappy.compress(zeros)) < len(zeros) // 8


def test_roundtrip_foreign_copy_op_streams():
    """Decode hand-built streams a real (optimal) snappy writer could
    emit — every copy encoding, including overlap — then verify OUR
    compressor round-trips the decoded payloads too."""
    streams = [
        # 1-byte-offset copy with the 3-bit length and offset high bits
        bytes([12]) + bytes([(8 - 1) << 2]) + b"abcdefgh"
        + bytes([(1 << 5) | ((4 - 4) << 2) | 1, 4]),   # off=260? no: off=(1<<8)|4
        # 2-byte-offset copy, maximum tag length (64)
        bytes([68 + 60]) + bytes([(60 - 1) << 2]) + bytes(range(60))
        + bytes([(64 - 1) << 2 | 2]) + (60).to_bytes(2, "little")
        + bytes([(4 - 1) << 2]) + b"done",
        # 4-byte-offset copy
        bytes([8]) + bytes([(4 - 1) << 2]) + b"wxyz"
        + bytes([(4 - 1) << 2 | 3]) + (4).to_bytes(4, "little"),
        # overlapping RLE: "ab" then copy(off=2, len=9)
        bytes([11]) + bytes([(2 - 1) << 2]) + b"ab"
        + bytes([(9 - 1) << 2 | 2]) + (2).to_bytes(2, "little"),
    ]
    for blob in streams:
        try:
            out = snappy.decompress(blob)
        except ValueError:
            # stream 0 intentionally uses offset high bits past the
            # produced output — either outcome must be clean
            continue
        assert snappy.decompress(snappy.compress(out)) == out


def test_long_literal_length_encodings():
    """Literals at the 60/61/62-byte-length-encoding boundaries."""
    rng = np.random.default_rng(14)
    for n in (59, 60, 61, 62, 255, 256, 65535, 65536, 100_000):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert snappy.decompress(snappy.compress(data)) == data


# -------------------------------------------------------------- robustness

def _must_be_clean(blob):
    """decompress either succeeds or raises ValueError — nothing else."""
    try:
        snappy.decompress(blob)
    except ValueError:
        pass


def test_truncations_never_crash():
    rng = np.random.default_rng(15)
    data = (b"abcdefgh" * 200
            + rng.integers(0, 256, 500, dtype=np.uint8).tobytes())
    comp = snappy.compress(data)
    for cut in range(0, len(comp), 7):
        _must_be_clean(comp[:cut])


def test_bit_flips_never_crash():
    rng = np.random.default_rng(16)
    data = (b"na" * 500
            + rng.integers(0, 256, 300, dtype=np.uint8).tobytes())
    comp = bytearray(snappy.compress(data))
    for _ in range(300):
        i = int(rng.integers(0, len(comp)))
        orig = comp[i]
        comp[i] ^= int(rng.integers(1, 256))
        _must_be_clean(bytes(comp))
        comp[i] = orig


def test_random_garbage_never_crashes():
    rng = np.random.default_rng(17)
    for _ in range(200):
        n = int(rng.integers(1, 400))
        _must_be_clean(rng.integers(0, 256, n, dtype=np.uint8).tobytes())


def test_hostile_streams_rejected():
    # declared length lies low AND high
    for declared in (0, 3, 5, 1 << 30):
        blob = bytes(write_uvarint(declared)) + bytes([(4 - 1) << 2]) \
            + b"abcd"
        if declared == 4:
            continue
        with pytest.raises(ValueError):
            snappy.decompress(blob)
    # copy reaching before the start of output
    with pytest.raises(ValueError):
        snappy.decompress(bytes([8]) + bytes([(4 - 1) << 2]) + b"abcd"
                          + bytes([(4 - 1) << 2 | 2])
                          + (5).to_bytes(2, "little"))
    # zero offset
    with pytest.raises(ValueError):
        snappy.decompress(bytes([8]) + bytes([(4 - 1) << 2]) + b"abcd"
                          + bytes([(4 - 1) << 2 | 2])
                          + (0).to_bytes(2, "little"))
    # literal length running past the end
    with pytest.raises(ValueError):
        snappy.decompress(bytes([100]) + bytes([(90 - 1) << 2]) + b"xy")
    # truncated 4-byte length encoding of a literal
    with pytest.raises(ValueError):
        snappy.decompress(bytes([10]) + bytes([(62) << 2]) + b"\x01")


def test_empty_input_rejected_empty_payload_ok():
    with pytest.raises(ValueError):
        snappy.decompress(b"")
    assert snappy.decompress(snappy.compress(b"")) == b""
