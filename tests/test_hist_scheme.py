"""Histogram bucket-scheme evolution (ref: HistogramBuckets.scala:340).

A series whose bucket scheme changes mid-retention must stay ingestible and
queryable: the dense store widens to the union scheme, paged-in chunks from
the old scheme are rebucketed, and cross-shard merges align schemes instead
of raising.
"""
import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.memory.histogram import rebucket, union_les
from filodb_tpu.query.engine import QueryEngine

START = 1_600_000_000_000
PROM_HISTOGRAM = DEFAULT_SCHEMAS["prom-histogram"]


def _hist_batch(num_series, num_samples, les, t0=START, seed=1,
                base_counts=None):
    """Histogram batch with explicit bucket boundaries."""
    rng = np.random.default_rng(seed)
    from filodb_tpu.ingest.generator import gauge_part_keys
    keys = gauge_part_keys(num_series, "http_latency")
    B = len(les)
    part_idx = np.repeat(np.arange(num_series, dtype=np.int32), num_samples)
    ts = np.tile(t0 + np.arange(num_samples, dtype=np.int64) * 10_000,
                 num_series)
    inc = rng.poisson(3.0, size=(num_series, num_samples, B))
    per_bucket = np.cumsum(inc, axis=1)
    if base_counts is not None:
        per_bucket += base_counts[:, None, :]
    hist = np.cumsum(per_bucket, axis=2).astype(np.float64)
    count = hist[:, :, -1]
    n = num_series * num_samples
    return RecordBatch(PROM_HISTOGRAM, keys, part_idx, ts,
                       {"sum": (count * 7.0).ravel(), "count": count.ravel(),
                        "h": hist.reshape(n, B)},
                       bucket_les=np.asarray(les, np.float64))


LES_A = [2.0, 4.0, 8.0, 16.0, float("inf")]
LES_B = [1.0, 4.0, 16.0, 64.0, float("inf")]


def test_rebucket_exact_at_shared_boundaries():
    src = np.array([1.0, 3.0, 6.0, 10.0, 12.0])     # cumulative over LES_A
    out = rebucket(src, LES_A, union_les(LES_A, LES_B))
    union = union_les(LES_A, LES_B)
    for le, v in zip(LES_A, src):
        assert out[list(union).index(le)] == v
    # monotone non-decreasing
    assert (np.diff(out) >= 0).all()


def test_live_scheme_change_widens_store():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(_hist_batch(4, 30, LES_A, t0=START))
    store = sh.stores["prom-histogram"]
    assert store.num_buckets == len(LES_A)
    # scheme changes mid-retention: later samples use LES_B
    sh.ingest(_hist_batch(4, 30, LES_B, t0=START + 30 * 10_000, seed=2))
    union = union_les(LES_A, LES_B)
    assert store.num_buckets == len(union)
    np.testing.assert_array_equal(store.bucket_les, union)
    # both halves are resident and cumulative-monotone per sample
    ts, cols, counts = store.gather_rows(np.arange(4))
    assert int(counts[0]) == 60
    h = cols["h"][0]
    valid = ~np.isnan(h[:, 0])
    assert valid.sum() == 60
    assert (np.diff(h[valid], axis=1) >= -1e-9).all()


def test_histogram_quantile_across_scheme_change():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(_hist_batch(4, 30, LES_A, t0=START))
    sh.ingest(_hist_batch(4, 30, LES_B, t0=START + 30 * 10_000, seed=2))
    eng = QueryEngine("prometheus", ms)
    s = START // 1000
    res = eng.query_range(
        'histogram_quantile(0.9, sum(rate(http_latency{_ws_="demo"}[5m])))',
        s + 350, 60, s + 580)
    assert res.error is None, res.error
    series = list(res.series())
    assert len(series) == 1
    vals = np.asarray(series[0][2])
    finite = vals[np.isfinite(vals)]
    assert finite.size > 0
    # quantiles live inside the union bucket range
    assert (finite >= 1.0).all() and (finite <= 64.0).all()


def test_paged_chunks_rebucket_after_scheme_change():
    """History flushed under scheme A, process restarts, live ingest under
    scheme B — the paged-in old chunks must rebucket, not drop."""
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh = ms.setup("prometheus", 0)
    sh.ingest(_hist_batch(3, 40, LES_A, t0=START), offset=1)
    sh.flush_all_groups()

    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh2 = ms2.setup("prometheus", 0)
    sh2.recover_index()
    sh2.ingest(_hist_batch(3, 40, LES_B, t0=START + 40 * 10_000, seed=5),
               offset=2)
    eng = QueryEngine("prometheus", ms2)
    s = START // 1000
    res = eng.query_range(
        'histogram_quantile(0.5, sum(rate(http_latency{_ws_="demo"}[5m])))',
        s + 350, 60, s + 780)
    assert res.error is None, res.error
    vals = np.asarray(list(res.series())[0][2])
    # windows in BOTH halves produce finite quantiles -> no dropped chunks
    assert np.isfinite(vals[:5]).any(), "old-scheme history missing"
    assert np.isfinite(vals[-5:]).any(), "new-scheme data missing"
    assert sh2.stats.rows_dropped == 0


LES_C = [3.0, 6.0, 12.0, 24.0, 48.0, float("inf")]


def test_paged_chunks_from_two_old_schemes():
    """History flushed under TWO different schemes (A then C), restart,
    live ingest under B: page-in must harmonize every chunk onto the final
    union scheme — a later chunk widening the store must not leave earlier
    decoded chunks at a stale width."""
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh = ms.setup("prometheus", 0)
    sh.ingest(_hist_batch(2, 30, LES_A, t0=START), offset=1)
    sh.flush_all_groups()
    sh.ingest(_hist_batch(2, 30, LES_C, t0=START + 30 * 10_000, seed=8),
              offset=2)
    sh.flush_all_groups()

    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh2 = ms2.setup("prometheus", 0)
    sh2.recover_index()
    sh2.ingest(_hist_batch(2, 30, LES_B, t0=START + 60 * 10_000, seed=9),
               offset=3)
    eng = QueryEngine("prometheus", ms2)
    s = START // 1000
    res = eng.query_range(
        'histogram_quantile(0.5, sum(rate(http_latency{_ws_="demo"}[5m])))',
        s + 350, 60, s + 880)
    assert res.error is None, res.error
    vals = np.asarray(list(res.series())[0][2])
    assert np.isfinite(vals[:3]).any(), "scheme-A history missing"
    assert np.isfinite(vals[-3:]).any(), "scheme-B live data missing"
    assert sh2.stats.rows_dropped == 0


def test_mixed_none_and_unequal_schemes_raises():
    """Two partials with DIFFERENT known schemes must not silently
    index-merge just because a third partial lacks boundaries."""
    from filodb_tpu.query.exec import AggPartial, reduce_partials
    from filodb_tpu.query.rangevector import RangeVectorKey
    wends = np.arange(3, dtype=np.int64)
    k = [RangeVectorKey.make({"g": "x"})]
    comp = np.ones((1, 3, 5))
    a = AggPartial("hist_sum", k, wends, comp=comp.copy(),
                   bucket_les=np.array([1.0, 2.0, 4.0, np.inf]))
    b = AggPartial("hist_sum", k, wends, comp=comp.copy(),
                   bucket_les=np.array([2.0, 4.0, 8.0, np.inf]))
    c = AggPartial("hist_sum", k, wends, comp=comp.copy(), bucket_les=None)
    for order in ([a, b, c], [c, a, b], [b, c, a]):
        with pytest.raises(ValueError):
            reduce_partials(order)


def test_boundaryless_width_mismatch_degrades_not_crashes():
    """A width-mismatched chunk paged into a boundary-less store must skip
    that chunk (rows_dropped), not fail the query (legacy behavior)."""
    from filodb_tpu.core.blockstore import DenseSeriesStore
    store = DenseSeriesStore(PROM_HISTOGRAM)
    row = store.new_row()
    h = np.cumsum(np.ones((5, 8)), axis=1)
    store.append_batch(np.full(5, row), START + np.arange(5) * 10_000,
                       {"sum": np.ones(5), "count": np.ones(5), "h": h},
                       bucket_les=None)
    assert store.bucket_les is None and store.num_buckets == 8
    with pytest.raises(ValueError):
        store.ensure_scheme(10, np.arange(10, dtype=float))


def test_hist_partial_merge_order_independent():
    """Mixed boundary-less + boundary-carrying hist partials of equal width
    must merge the same way regardless of child order."""
    from filodb_tpu.query.exec import AggPartial, reduce_partials
    from filodb_tpu.query.rangevector import RangeVectorKey
    wends = np.arange(3, dtype=np.int64)
    k = [RangeVectorKey.make({"g": "x"})]
    comp = np.ones((1, 3, 5))           # 4 buckets + present count
    a = AggPartial("hist_sum", k, wends, comp=comp.copy(), bucket_les=None)
    b = AggPartial("hist_sum", k, wends, comp=comp.copy(),
                   bucket_les=np.array([1.0, 2.0, 4.0, np.inf]))
    r1 = reduce_partials([a, b])
    r2 = reduce_partials([b, a])
    np.testing.assert_allclose(r1.comp, r2.comp)


def test_cross_shard_scheme_merge():
    """Shard 0 carries scheme A, shard 1 scheme B; sum(rate()) must merge
    on the union scheme instead of raising."""
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0)
    ms.setup("prometheus", 1)
    a = _hist_batch(2, 30, LES_A, seed=3)
    b = _hist_batch(2, 30, LES_B, seed=4)
    # distinct series identities on shard 1
    from filodb_tpu.core.partkey import PartKey
    keys_b = [PartKey.make("http_latency",
                           {**dict(pk.tags), "instance": f"s1-{i}"})
              for i, pk in enumerate(b.part_keys)]
    b = RecordBatch(b.schema, keys_b, b.part_idx, b.timestamps, b.columns,
                    b.bucket_les)
    ms.ingest("prometheus", 0, a, offset=1)
    ms.ingest("prometheus", 1, b, offset=1)
    mapper = ShardMapper(2)
    for s_num in (0, 1):
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", s_num, "node"))
    eng = QueryEngine("prometheus", ms, mapper)
    s = START // 1000
    res = eng.query_range(
        'histogram_quantile(0.9, sum(rate(http_latency{_ws_="demo"}[5m])))',
        s + 200, 30, s + 290)
    assert res.error is None, res.error
    vals = np.asarray(list(res.series())[0][2])
    assert np.isfinite(vals).any()


def test_histogram_quantile_numpy_twin_parity():
    """The host numpy histogram_quantile must match the jnp version
    bit-for-bit across the semantic edge cases (round-5 item 5: the
    numpy twin removes a per-panel device dispatch)."""
    import numpy as np
    import jax.numpy as jnp
    from filodb_tpu.ops import hist as hist_ops

    rng = np.random.default_rng(3)
    les = np.array([0.5, 1.0, 2.5, 10.0, np.inf])
    buckets = np.cumsum(rng.poisson(3.0, (7, 11, 5)).astype(np.float64),
                        axis=-1)
    buckets[0, 0] = 0.0                      # empty histogram -> NaN
    buckets[1, 2, -1] = buckets[1, 2, -2]    # all mass below +Inf bucket
    for q in (-0.5, 0.0, 0.25, 0.9, 0.999, 1.0, 1.5):
        a = np.asarray(hist_ops._histogram_quantile_np(q, buckets, les))
        b = np.asarray(hist_ops.histogram_quantile(
            q, jnp.asarray(buckets), jnp.asarray(les)))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-12,
                                   equal_nan=True)
    # the dispatcher itself picks numpy for host arrays
    got = hist_ops.histogram_quantile(0.9, buckets, les)
    assert isinstance(got, np.ndarray)
