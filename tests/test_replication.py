"""Replication layer tests (filodb_tpu/replication; doc/replication.md):
placement math, ingest fan-out + lag journal edges, WAL-segment
catch-up, query-time replica failover + gather dedup, the live-handoff
state machine, health/admin surfaces.

Fast in-process tests run in tier-1; traffic-under-chaos drills carry
the `replication` marker (implies slow) and run via -m replication or
`python bench.py replication`.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.schemas import PROM_COUNTER
from filodb_tpu.parallel.shardmanager import (DatasetResourceSpec,
                                              ShardManager)
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             ShardStatus)
from filodb_tpu.parallel.testcluster import make_replicated_cluster
from filodb_tpu.query.rangevector import PlannerParams
from filodb_tpu.utils.events import journal
from filodb_tpu.utils.jobs import jobs

DS = "prometheus"
START = 1_600_000_000_000


def _keys(n, ns="n"):
    return [PartKey.make("repl_total",
                         {"_ws_": "w", "_ns_": ns, "i": str(i)})
            for i in range(n)]


def _grid(n_series, n_samples, base_idx=0):
    ts = (np.arange(n_samples, dtype=np.int64)[None, :]
          + base_idx) * 10_000 + START
    ts = np.repeat(ts, n_series, axis=0)
    vals = (np.arange(n_samples, dtype=np.float64)[None, :] + base_idx) \
        * 5.0 + np.arange(n_series, dtype=np.float64)[:, None]
    return ts, vals


# ------------------------------------------------------------- placement


def test_mapper_ordered_owners_and_promotion():
    m = ShardMapper(4, replication_factor=2)
    m.update_from_event(ShardEvent("IngestionStarted", DS, 0, "A"))
    m.register_replica(0, "B", status=ShardStatus.ACTIVE)
    assert m.owners(0) == ["A", "B"]
    assert m.live_owners(0) == ["A", "B"]
    # registering the primary as replica is a no-op
    m.register_replica(0, "A")
    assert m.owners(0) == ["A", "B"]
    old = m.promote_replica(0, "B", demote_old=True)
    assert old == "A"
    assert m.owners(0) == ["B", "A"]
    assert m.node_for_shard(0) == "B"
    # promoted replica carried its ACTIVE status into the primary column
    assert m.statuses[0] == ShardStatus.ACTIVE
    m.unassign_replica(0, "A")
    assert m.owners(0) == ["B"]
    with pytest.raises(ValueError):
        m.promote_replica(0, "Z")


def test_mapper_replica_events_never_touch_primary():
    m = ShardMapper(2)
    m.update_from_event(ShardEvent("IngestionStarted", DS, 0, "A"))
    m.update_from_event(ShardEvent("ReplicaAssigned", DS, 0, "B"))
    assert m.owner_status(0, "B") == ShardStatus.ASSIGNED
    m.update_from_event(ShardEvent("ReplicaActive", DS, 0, "B"))
    assert m.owner_status(0, "B") == ShardStatus.ACTIVE
    assert m.statuses[0] == ShardStatus.ACTIVE      # primary untouched
    # a ShardDown addressed to the REPLICA node removes only the replica
    m.update_from_event(ShardEvent("ShardDown", DS, 0, "B"))
    assert m.owners(0) == ["A"]
    assert m.node_for_shard(0) == "A"
    assert m.statuses[0] == ShardStatus.ACTIVE
    # ReplicaPromoted event = the atomic cutover
    m.update_from_event(ShardEvent("ReplicaAssigned", DS, 0, "C"))
    m.update_from_event(ShardEvent("ReplicaPromoted", DS, 0, "C"))
    assert m.node_for_shard(0) == "C"
    assert "A" not in m.owners(0)


def test_manager_rf2_never_colocates():
    sm = ShardManager(replication_factor=2)
    for n in ("a", "b", "c"):
        sm.add_member(n)
    mapper = sm.setup_dataset(DS, DatasetResourceSpec(8, 3))
    for s in range(8):
        owners = mapper.owners(s)
        assert len(owners) == 2, f"shard {s}: {owners}"
        assert len(set(owners)) == 2, f"shard {s} co-located: {owners}"


def test_manager_promotes_replica_on_primary_death():
    sm = ShardManager(replication_factor=2)
    for n in ("a", "b", "c"):
        sm.add_member(n)
    mapper = sm.setup_dataset(DS, DatasetResourceSpec(8, 3))
    # all copies live
    for s in range(8):
        sm.on_shard_event(ShardEvent("IngestionStarted", DS, s,
                                     mapper.node_for_shard(s)))
        for n in list(mapper.replicas[s]):
            sm.on_shard_event(ShardEvent("ReplicaActive", DS, s, n))
    victim = mapper.node_for_shard(0)
    owned = mapper.shards_for_node(victim)
    sm.remove_member(victim)
    for s in owned:
        # never Down: the live replica was promoted in place
        assert mapper.statuses[s] == ShardStatus.ACTIVE, \
            f"shard {s} went {mapper.statuses[s]} instead of promoting"
        assert mapper.node_for_shard(s) != victim
    # the dead node is gone from every assignment list
    assert not mapper.replica_shards_for_node(victim)
    # replicas refilled on surviving capacity (2 nodes left -> every
    # shard can still hold 2 distinct owners)
    for s in range(8):
        assert len(set(mapper.owners(s))) == 2


def test_mapper_replication_off_unchanged():
    m = ShardMapper(4)
    assert m.replication_factor == 1
    assert m.replicas == [[], [], [], []]
    m.update_from_event(ShardEvent("IngestionStarted", DS, 1, "A"))
    assert m.owners(1) == ["A"]


# -------------------------------------------- satellite: mapper edge math


def test_mapper_rejects_non_power_of_two():
    with pytest.raises(AssertionError):
        ShardMapper(6)
    with pytest.raises(AssertionError):
        ShardMapper(0)


def test_shard_down_clears_node_assignment():
    m = ShardMapper(4)
    m.update_from_event(ShardEvent("IngestionStarted", DS, 2, "A"))
    assert m.node_for_shard(2) == "A"
    m.update_from_event(ShardEvent("ShardDown", DS, 2, "A"))
    assert m.node_for_shard(2) is None
    assert m.statuses[2] == ShardStatus.DOWN
    assert not m.active_shards([2])


def test_query_shards_run_boundaries():
    """queryShards returns the full 2^spread-wide aligned run the shard
    key can land on — and clamps spread past log2(numShards)."""
    m = ShardMapper(8)
    h = 0b10110  # arbitrary shard-key hash
    assert m.query_shards(h, 0) == [h & 7]
    run = m.query_shards(h, 2)
    assert len(run) == 4
    base = run[0]
    assert base % 4 == 0                     # aligned to the run width
    assert run == [base, base + 1, base + 2, base + 3]
    # ingestion_shard always lands inside the query run
    for ph in range(64):
        assert m.ingestion_shard(h, ph, 2) in run
    # spread beyond log2(numShards) clamps to all shards
    assert m.query_shards(h, 10) == list(range(8))


# ---------------------------------------------------------- ingest fan-out


def test_fanout_quorum_ack_and_lag_journal_edges():
    cluster = make_replicated_cluster(num_shards=2)
    try:
        keys = _keys(8)
        ts, vals = _grid(8, 16)
        res = cluster.ingest_grid(0, PROM_COUNTER.name, keys, ts,
                                  {"count": vals})
        owners = cluster.mapper.owners(0)
        assert sorted(res.acked) == sorted(owners)
        for n in owners:
            sh = cluster.stores[n].get_shard(DS, 0)
            assert sh.num_partitions == 8
        # kill one replica owner -> fan-out marks it lagging (journal
        # edge fires once), primary ack keeps ingest available
        replica = cluster.mapper.replicas[0][0]
        seq0 = journal.next_seq
        cluster.kill(replica)
        for b in range(3):
            ts2, vals2 = _grid(8, 4, base_idx=16 + b * 4)
            res2 = cluster.ingest_grid(0, PROM_COUNTER.name, keys, ts2,
                                       {"count": vals2})
            assert cluster.mapper.node_for_shard(0) in res2.acked
            assert replica not in res2.acked
        lag_events = [e for e in journal.since(seq0 - 1)
                      if e["kind"] == "replica_lagging"
                      and e.get("peer") == replica]
        assert len(lag_events) == 1, "lagging edge must fire exactly once"
        snap = cluster.manager.snapshot()
        lagging = [p for p in snap if p["peer"] == replica]
        assert lagging and lagging[0]["lagging"]
    finally:
        cluster.stop()


def test_fanout_requires_some_owner():
    from filodb_tpu.replication.replicator import ReplicationSendError
    cluster = make_replicated_cluster(num_shards=2)
    try:
        for n in list(cluster.mapper.owners(1)):
            cluster.kill(n)
        keys = _keys(4)
        ts, vals = _grid(4, 4)
        with pytest.raises(ReplicationSendError):
            cluster.manager.replicate(1, PROM_COUNTER.name, keys, ts,
                                      {"count": vals},
                                      require_primary=True)
    finally:
        cluster.stop()


# ------------------------------------------------------- WAL catch-up


def test_catchup_streams_segments_and_registers_job(tmp_path):
    from filodb_tpu.replication import (ReplicaClient, ReplicationServer,
                                        catchup_shards)
    from filodb_tpu.wal import WalManager
    ms_primary = TimeSeriesMemStore()
    ms_primary.setup(DS, 0)
    ms_primary.setup(DS, 1)
    wal = WalManager(str(tmp_path), DS)
    keys = _keys(6)
    for shard in (0, 1):
        for b in range(4):
            ts, vals = _grid(6, 8, base_idx=b * 8)
            seq = wal.append_grid(shard, PROM_COUNTER.name, keys, ts,
                                  {"count": vals})
            ms_primary.get_shard(DS, shard).ingest_columns(
                PROM_COUNTER.name, keys, ts, {"count": vals}, offset=seq)
    srv = ReplicationServer(ms_primary, node="P", wals={DS: wal}).start()
    try:
        cli = ReplicaClient(*srv.address)
        replica = TimeSeriesMemStore()
        stats = catchup_shards(cli, DS, replica, shards=[1], node="R")
        assert stats.records == 4
        assert stats.samples == 4 * 6 * 8
        # only the filtered shard materialized
        assert replica.get_shard(DS, 0) is None
        sh = replica.get_shard(DS, 1)
        assert sh.num_partitions == 6
        # replayed data answers identically to the primary's copy
        a = ms_primary.get_shard(DS, 1).stores[PROM_COUNTER.name]
        b = sh.stores[PROM_COUNTER.name]
        assert a.num_series == b.num_series
        # resume point: nothing replays twice
        stats2 = catchup_shards(cli, DS, replica, shards=[1],
                                since={1: stats.last_seq}, node="R")
        assert stats2.records == 0
        # the PR 10 job registry saw the runs
        h = jobs.get("replication_catchup", dataset=DS)
        assert h is not None and h.runs >= 2 and h.consecutive_errors == 0
        caught = [e for e in journal.since(0)
                  if e["kind"] == "replica_caught_up"
                  and e.get("node") == "R"]
        assert caught
    finally:
        srv.stop()
        wal.close()


def test_wal_snapshot_segments_safe_bytes(tmp_path):
    """The active segment's snapshot byte range decodes completely —
    whole frames only, no torn tail inside safe_bytes."""
    from filodb_tpu.wal.segment import WalRecord, read_records
    from filodb_tpu.wal.writer import WalWriter
    w = WalWriter(str(tmp_path), dataset=DS)
    keys = _keys(4)
    for b in range(5):
        ts, vals = _grid(4, 8, base_idx=b * 8)
        w.append(WalRecord(0, 0, PROM_COUNTER.name, keys, ts,
                           {"count": vals}))
    segs, committed = w.snapshot_segments()
    assert committed == 4
    assert segs, "active segment must appear in the snapshot"
    first, last, path, safe = segs[-1]
    assert last == 4
    data = open(path, "rb").read(safe)
    clone = str(tmp_path / "clone.seg")
    with open(clone, "wb") as f:
        f.write(data)
    tables = {}
    seqs = [WalRecord.decode(body, tables).seq
            for body in read_records(clone)]
    assert seqs == [0, 1, 2, 3, 4]
    w.close()


# ------------------------------------------------- query-time failover


def _fill_cluster(cluster, n_series=32, n_samples=64):
    keys = _keys(n_series)
    ts, vals = _grid(n_series, n_samples)
    for s in range(cluster.mapper.num_shards):
        skeys = [PartKey.make("repl_total",
                             {"_ws_": "w", "_ns_": f"s{s}",
                              "i": str(i)}) for i in range(n_series)]
        cluster.ingest_grid(s, PROM_COUNTER.name, skeys, ts,
                            {"count": vals})
    return keys, ts, vals


QUERY = 'sum by (_ns_)(rate(repl_total[5m]))'
QS = START // 1000 + 600
QE = START // 1000 + 630


def _payload(res):
    from filodb_tpu.query.engine import QueryEngine
    p = QueryEngine.to_prom_matrix(res)
    p.pop("traceID", None)
    return json.dumps(p, sort_keys=True)


def test_failover_serves_full_results_through_node_kill():
    from filodb_tpu.parallel.breaker import breakers
    from filodb_tpu.utils.metrics import registry
    breakers.reset()
    cluster = make_replicated_cluster(num_shards=2, with_truth=True)
    try:
        _fill_cluster(cluster)
        pp = PlannerParams(allow_partial_results=True)
        baseline = cluster.engine.query_range(QUERY, QS, 30, QE, pp)
        assert baseline.error is None and not baseline.partial
        groups = {k.labels_dict.get("_ns_")
                  for k, _, _ in baseline.series()}
        assert groups == {"s0", "s1"}
        # kill one node: every query stays FULL via replica failover
        victim = cluster.mapper.node_for_shard(0)
        fo0 = registry.counter("query_replica_failovers",
                               peer=cluster.mapper.replicas[0][0]).value
        cluster.kill(victim)
        for _ in range(4):
            res = cluster.engine.query_range(QUERY, QS, 30, QE, pp)
            assert res.error is None, res.error
            assert not res.partial, "failover must beat the partial path"
            got = {k.labels_dict.get("_ns_") for k, _, _ in res.series()}
            assert got == {"s0", "s1"}, f"missing groups: {got}"
            assert _payload(res) == _payload(baseline)
        fo1 = registry.counter("query_replica_failovers",
                               peer=cluster.mapper.replicas[0][0]).value
        assert fo1 > fo0, "failover counter must prove the replica served"
    finally:
        cluster.stop()
        breakers.reset()


def test_partials_only_when_all_owners_dead():
    from filodb_tpu.parallel.breaker import breakers
    breakers.reset()
    cluster = make_replicated_cluster(num_shards=2)
    try:
        _fill_cluster(cluster)
        # kill EVERY owner of shard 0; shard 1 keeps at least one owner
        dead = set(cluster.mapper.owners(0))
        survivors = [n for n in cluster.mapper.owners(1)
                     if n not in dead]
        assert survivors, "fixture must leave shard 1 an owner"
        for n in dead:
            cluster.kill(n)
        pp = PlannerParams(allow_partial_results=True)
        res = cluster.engine.query_range(QUERY, QS, 30, QE, pp)
        assert res.error is None, res.error
        assert res.partial, "all owners dead -> flagged partial"
        got = {k.labels_dict.get("_ns_") for k, _, _ in res.series()}
        assert "s0" not in got
    finally:
        cluster.stop()
        breakers.reset()


# ------------------------------------------------------- gather dedup


def test_gather_dedups_duplicate_shard_children():
    """Both owners of a shard materialized (handoff window): the shard
    contributes exactly once to concat AND aggregation."""
    from filodb_tpu.core.index import Equals
    from filodb_tpu.query.exec import (AggregateMapReduce,
                                       AggregatePresenter,
                                       LocalPartitionDistConcatExec,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper,
                                       ReduceAggregateExec)
    from filodb_tpu.query.rangevector import QueryContext
    from filodb_tpu.utils.metrics import registry
    ms = TimeSeriesMemStore()
    ms.setup(DS, 0)
    keys = _keys(8)
    ts, vals = _grid(8, 64)
    ms.get_shard(DS, 0).ingest_columns(PROM_COUNTER.name, keys, ts,
                                       {"count": vals})

    def leaf():
        lf = MultiSchemaPartitionsExec(
            QueryContext(), DS, 0, [Equals("_metric_", "repl_total")],
            START, START + 64 * 10_000)
        lf.add_transformer(PeriodicSamplesMapper(
            QS * 1000, 30_000, QE * 1000, 300_000, "rate", ()))
        lf.add_transformer(AggregateMapReduce("sum", (), ("_ns_",), ()))
        return lf

    single = ReduceAggregateExec(QueryContext(), [leaf()], "sum")
    single.add_transformer(AggregatePresenter("sum", ()))
    want = single.execute(ms)
    assert want.error is None

    before = registry.counter("query_shard_dedup").value
    dup = ReduceAggregateExec(QueryContext(), [leaf(), leaf()], "sum")
    dup.add_transformer(AggregatePresenter("sum", ()))
    got = dup.execute(ms)
    assert got.error is None
    assert registry.counter("query_shard_dedup").value > before
    np.testing.assert_allclose(np.asarray(got.blocks[0].values),
                               np.asarray(want.blocks[0].values))

    # concat path too: series count must not double
    def leaf_raw():
        lf = MultiSchemaPartitionsExec(
            QueryContext(), DS, 0, [Equals("_metric_", "repl_total")],
            START, START + 64 * 10_000)
        lf.add_transformer(PeriodicSamplesMapper(
            QS * 1000, 30_000, QE * 1000, 300_000, "rate", ()))
        return lf

    single_cat = LocalPartitionDistConcatExec(QueryContext(),
                                              [leaf_raw()])
    want_cat = single_cat.execute(ms)
    cat = LocalPartitionDistConcatExec(QueryContext(),
                                       [leaf_raw(), leaf_raw()])
    res = cat.execute(ms)
    assert res.error is None
    assert len(res.blocks[0].keys) == len(want_cat.blocks[0].keys)


def test_gather_never_dedups_different_selectors_on_one_shard():
    """Regression: a ShardKeyRegexPlanner fan-out legitimately puts two
    same-shard leaves with DIFFERENT selectors under one concat — the
    dedup key must include the selector, or one combo's data silently
    vanishes from a FULL result."""
    from filodb_tpu.core.index import Equals
    from filodb_tpu.query.exec import (LocalPartitionDistConcatExec,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper)
    from filodb_tpu.query.rangevector import QueryContext
    ms = TimeSeriesMemStore()
    ms.setup(DS, 0)
    ts, vals = _grid(4, 64)
    for ns in ("a", "b"):
        keys = [PartKey.make("repl_total",
                             {"_ws_": "w", "_ns_": ns, "i": str(i)})
                for i in range(4)]
        ms.get_shard(DS, 0).ingest_columns(PROM_COUNTER.name, keys, ts,
                                           {"count": vals})

    def leaf(ns):
        lf = MultiSchemaPartitionsExec(
            QueryContext(), DS, 0,
            [Equals("_metric_", "repl_total"), Equals("_ns_", ns)],
            START, START + 64 * 10_000)
        lf.add_transformer(PeriodicSamplesMapper(
            QS * 1000, 30_000, QE * 1000, 300_000, "rate", ()))
        return lf

    cat = LocalPartitionDistConcatExec(QueryContext(),
                                       [leaf("a"), leaf("b")])
    res = cat.execute(ms)
    assert res.error is None
    got_ns = {k.labels_dict.get("_ns_") for k in res.blocks[0].keys}
    assert got_ns == {"a", "b"}, \
        f"a shard-key combo was wrongly deduped away: {got_ns}"


def test_gather_twin_absorbs_shard_unavailable():
    """First-listed owner dead, duplicate twin healthy: the twin answers
    — no partial flag, no error (the handoff-window contract)."""
    from filodb_tpu.core.index import Equals
    from filodb_tpu.query.exec import (AggregateMapReduce,
                                       AggregatePresenter,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper,
                                       QueryError,
                                       ReduceAggregateExec)
    from filodb_tpu.query.execbase import PlanDispatcher
    from filodb_tpu.query.rangevector import QueryContext
    ms = TimeSeriesMemStore()
    ms.setup(DS, 0)
    keys = _keys(4)
    ts, vals = _grid(4, 64)
    ms.get_shard(DS, 0).ingest_columns(PROM_COUNTER.name, keys, ts,
                                       {"count": vals})

    class _DeadDispatcher(PlanDispatcher):
        def dispatch(self, plan, source):
            raise QueryError("shard_unavailable", "owner SIGKILLed")

    def leaf(dead=False):
        lf = MultiSchemaPartitionsExec(
            QueryContext(), DS, 0, [Equals("_metric_", "repl_total")],
            START, START + 64 * 10_000)
        lf.add_transformer(PeriodicSamplesMapper(
            QS * 1000, 30_000, QE * 1000, 300_000, "rate", ()))
        lf.add_transformer(AggregateMapReduce("sum", (), ("_ns_",), ()))
        if dead:
            lf.dispatcher = _DeadDispatcher()
        return lf

    want = ReduceAggregateExec(QueryContext(), [leaf()], "sum")
    want.add_transformer(AggregatePresenter("sum", ()))
    base = want.execute(ms)

    plan = ReduceAggregateExec(QueryContext(),
                               [leaf(dead=True), leaf()], "sum")
    plan.add_transformer(AggregatePresenter("sum", ()))
    res = plan.execute(ms)
    assert res.error is None, res.error
    assert not res.partial
    np.testing.assert_allclose(np.asarray(res.blocks[0].values),
                               np.asarray(base.blocks[0].values))


# ------------------------------------------------------------- handoff


def test_handoff_state_machine_and_journal():
    cluster = make_replicated_cluster(nodes=("A", "B", "C"),
                                      num_shards=2, with_truth=True)
    try:
        _fill_cluster(cluster)
        pp = PlannerParams()
        baseline = cluster.engine.query_range(QUERY, QS, 30, QE, pp)
        assert baseline.error is None
        shard = 0
        from_node = cluster.mapper.node_for_shard(shard)
        owners = set(cluster.mapper.owners(shard))
        target = next(n for n in ("A", "B", "C") if n not in owners)
        from filodb_tpu.replication import HandoffCoordinator
        coord = HandoffCoordinator(DS, cluster.mapper,
                                   lambda n: cluster.repl_clients[n])
        seq0 = journal.next_seq
        summary = coord.handoff(shard, target)
        assert summary["states"][-1] == "done"
        assert cluster.mapper.node_for_shard(shard) == target
        assert from_node not in cluster.mapper.owners(shard)
        # the old owner's copy was tombstoned
        assert cluster.stores[from_node].get_shard(DS, shard) is None
        # the new owner answers; results byte-identical to pre-handoff
        res = cluster.engine.query_range(QUERY, QS, 30, QE, pp)
        assert res.error is None and not res.partial
        assert _payload(res) == _payload(baseline)
        kinds = [e["kind"] for e in journal.since(seq0 - 1)]
        assert "shard_handoff_started" in kinds
        assert "shard_handoff_done" in kinds
        states = [e["state"] for e in journal.since(seq0 - 1)
                  if e["kind"] == "shard_handoff"]
        assert states == ["register", "stream_snapshot",
                          "stream_wal_tail", "cutover", "tombstone",
                          "done"]
    finally:
        cluster.stop()


def test_handoff_failure_journals_and_rolls_back():
    from filodb_tpu.replication import (HandoffCoordinator, HandoffError,
                                        ReplicaClient)
    cluster = make_replicated_cluster(nodes=("A", "B", "C"),
                                      num_shards=2)
    try:
        _fill_cluster(cluster)
        shard = 0
        owners_before = list(cluster.mapper.owners(shard))
        target = next(n for n in ("A", "B", "C")
                      if n not in owners_before)
        # target's replication door is dead -> the snapshot stream fails
        cluster.repl_servers[target].stop()
        dead_client = ReplicaClient(*cluster.repl_servers[target].address,
                                    timeout_s=1.0)

        def client_for(n):
            return dead_client if n == target \
                else cluster.repl_clients[n]

        coord = HandoffCoordinator(DS, cluster.mapper, client_for)
        seq0 = journal.next_seq
        with pytest.raises(HandoffError):
            coord.handoff(shard, target)
        fails = [e for e in journal.since(seq0 - 1)
                 if e["kind"] == "shard_handoff_failed"]
        assert fails and fails[0]["state"] in ("register",
                                               "stream_snapshot")
        # rollback: the half-registered target left the assignment list
        assert cluster.mapper.owners(shard) == owners_before
    finally:
        cluster.stop()


# ------------------------------------------------- health + admin surface


def test_health_degrades_on_zero_live_replicas():
    from filodb_tpu.utils.health import (DEGRADED, FAILED, OK,
                                         HealthEvaluator, SERVING)
    ev = HealthEvaluator(phase=SERVING)
    m = ShardMapper(2, replication_factor=2)
    ev.shard_mappers = {DS: m}
    for s in (0, 1):
        m.update_from_event(ShardEvent("IngestionStarted", DS, s, "A"))
        m.register_replica(s, "B", status=ShardStatus.ACTIVE)
    assert ev._shards_verdict()["status"] == OK
    # replica of shard 0 dies: primary serves, but one failure from
    # partials -> degraded
    m.unassign_replica(0, "B")
    sv = ev._shards_verdict()
    assert sv["status"] == DEGRADED
    assert sv["datasets"][DS]["underReplicated"] == 1
    # every owner of shard 0 dead -> failed
    m.update_from_event(ShardEvent("ShardDown", DS, 0, "A"))
    sv = ev._shards_verdict()
    assert sv["status"] == FAILED
    assert sv["datasets"][DS]["noLiveOwners"] == 1


def test_ready_503_while_draining():
    from filodb_tpu.utils.health import HealthEvaluator, SERVING
    ev = HealthEvaluator(phase=SERVING)
    ok, _ = ev.ready()
    assert ok
    ev.draining = "drained 4 shard(s) off A"
    ok, reason = ev.ready()
    assert not ok and "draining" in reason


def test_admin_shards_route_and_cli_shape():
    from filodb_tpu.http.routes import PromHttpApi
    api = PromHttpApi({})
    m = ShardMapper(2, replication_factor=2)
    m.update_from_event(ShardEvent("IngestionStarted", DS, 0, "A"))
    m.register_replica(0, "B", status=ShardStatus.ACTIVE)
    api.shard_mappers[DS] = m
    st, payload = api.handle("GET", "/admin/shards", {})
    assert st == 200
    ent = payload["data"]["datasets"][DS]
    assert ent["replicationFactor"] == 2
    row = ent["shards"][0]
    assert row["primary"] == "A"
    assert row["replicas"] == [{"node": "B", "status": "Active"}]
    assert row["liveOwners"] == 2
    st, _ = api.handle("GET", "/admin/shards", {"dataset": "nope"})
    assert st == 404
    # handoff route without a coordinator is a clean 400
    st, payload = api.handle("POST", "/admin/shards/0/handoff",
                             {"to": "B"}, b"")
    assert st == 400


def test_admin_shards_handoff_route_drives_coordinator():
    cluster = make_replicated_cluster(nodes=("A", "B", "C"),
                                      num_shards=2)
    try:
        _fill_cluster(cluster)
        from filodb_tpu.http.routes import PromHttpApi
        from filodb_tpu.replication import HandoffCoordinator
        api = PromHttpApi({})
        api.default_dataset = DS
        api.shard_mappers[DS] = cluster.mapper
        api.handoffs[DS] = HandoffCoordinator(
            DS, cluster.mapper, lambda n: cluster.repl_clients[n])
        shard = 0
        owners = set(cluster.mapper.owners(shard))
        target = next(n for n in ("A", "B", "C") if n not in owners)
        st, payload = api.handle(
            "POST", f"/admin/shards/{shard}/handoff",
            {"drain": "true"},
            json.dumps({"to": target}).encode())
        assert st == 200, payload
        assert payload["data"]["to"] == target
        assert cluster.mapper.node_for_shard(shard) == target
        # drain=true flipped readiness
        ok, reason = api.health.ready()
        assert not ok and "handed off" in reason
        # a bad target is a structured 409, not a 500
        st, payload = api.handle(
            "POST", f"/admin/shards/{shard}/handoff", {},
            json.dumps({"to": target}).encode())
        assert st == 409
    finally:
        cluster.stop()


# ----------------------------------- chaos-style: traffic through handoff


@pytest.mark.replication
def test_live_handoff_under_traffic_zero_failed_queries():
    """The acceptance drill: ingest+query traffic runs while a shard
    hands off — zero failed queries, zero partials, and the final
    query_range is byte-identical to an undisturbed truth store."""
    cluster = make_replicated_cluster(nodes=("A", "B", "C"),
                                      num_shards=2, with_truth=True)
    try:
        n_series, n_samples = 16, 64
        skeys = {s: [PartKey.make("repl_total",
                                  {"_ws_": "w", "_ns_": f"s{s}",
                                   "i": str(i)})
                     for i in range(n_series)]
                 for s in range(2)}
        ts, vals = _grid(n_series, n_samples)
        for s in range(2):
            cluster.ingest_grid(s, PROM_COUNTER.name, skeys[s], ts,
                                {"count": vals})
        stop = threading.Event()
        qerrs, qpartials, qok = [], [], [0]
        tick = [n_samples]

        def query_loop():
            pp = PlannerParams(allow_partial_results=True)
            while not stop.is_set():
                res = cluster.engine.query_range(QUERY, QS, 30, QE, pp)
                if res.error is not None:
                    qerrs.append(res.error)
                elif res.partial:
                    qpartials.append(True)
                else:
                    qok[0] += 1
                time.sleep(0.02)

        def ingest_loop():
            while not stop.is_set():
                b = tick[0]
                tick[0] += 1
                for s in range(2):
                    ts2, vals2 = _grid(n_series, 1, base_idx=b)
                    cluster.ingest_grid(s, PROM_COUNTER.name, skeys[s],
                                        ts2, {"count": vals2})
                time.sleep(0.02)

        threads = [threading.Thread(target=query_loop, daemon=True),
                   threading.Thread(target=ingest_loop, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        from filodb_tpu.replication import HandoffCoordinator
        shard = 0
        owners = set(cluster.mapper.owners(shard))
        target = next(n for n in ("A", "B", "C") if n not in owners)
        coord = HandoffCoordinator(DS, cluster.mapper,
                                   lambda n: cluster.repl_clients[n])
        summary = coord.handoff(shard, target)
        assert summary["states"][-1] == "done"
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not qerrs, f"queries failed during handoff: {qerrs[:3]}"
        assert not qpartials, "no partials during a handoff"
        # CPU XLA recompiles per fresh-shape poll make the loop slow;
        # the gates above cover every query that DID run
        assert qok[0] >= 1
        # quiesce: final answer identical to the undisturbed truth store
        res = cluster.engine.query_range(QUERY, QS, 30, QE,
                                         PlannerParams())
        from filodb_tpu.query.engine import QueryEngine
        tmapper = ShardMapper(2)
        for s in range(2):
            tmapper.update_from_event(
                ShardEvent("IngestionStarted", DS, s, "local"))
        truth_engine = QueryEngine(DS, cluster.truth, tmapper)
        want = truth_engine.query_range(QUERY, QS, 30, QE,
                                        PlannerParams())
        assert res.error is None and want.error is None
        got = {k.labels_dict["_ns_"]: np.asarray(v)
               for k, _, v in res.series()}
        exp = {k.labels_dict["_ns_"]: np.asarray(v)
               for k, _, v in want.series()}
        assert set(got) == {"s0", "s1"}
        for g in got:
            np.testing.assert_allclose(got[g], exp[g])
    finally:
        cluster.stop()
