"""engine.query_range_batch: a dashboard's panels over one window grid
merge compatible fused leaves into single kernel dispatches (multi-hot
epilogue, ops/pallas_fused.fused_leaf_agg_batch) with results identical
to the queries run one at a time.

The reference has no analogue (its iterator engine pays per-series cost
either way); this is the TPU-shaped answer to the round-4 on-chip
finding that fused leaf queries are dispatch-bound (doc/kernels.md)."""
import numpy as np
import pytest

from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.utils.metrics import registry

from test_query_engine import _mk_engine

START_MS = 1_600_000_000_000
START_S = START_MS // 1000
T = 240
END_S = START_S + T * 10

PANELS = [
    'sum(rate(request_total{_ws_="demo"}[5m])) by (_ns_)',
    'avg(rate(request_total{_ws_="demo"}[5m])) by (dc)',
    'sum(rate(request_total{_ws_="demo"}[5m])) by (_ns_, dc)',
    'count(rate(request_total{_ws_="demo"}[5m])) by (dc)',
    'min(rate(request_total{_ws_="demo"}[5m])) by (_ns_)',
    'max(rate(request_total{_ws_="demo"}[5m])) by (dc)',
]


@pytest.fixture()
def fused_env(monkeypatch):
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")


def _series_map(res):
    assert res.error is None, res.error
    return {tuple(sorted(k.labels_dict.items())): np.asarray(v)
            for k, _, v in res.series()}


def _mk(batches=None):
    return _mk_engine(batches or [counter_batch(60, T, start_ms=START_MS,
                                                resets=True)])


def test_batch_matches_individual_queries(fused_env):
    engine = _mk()
    args = (START_S + 600, 60, END_S)
    want = [_series_map(engine.query_range(q, *args)) for q in PANELS]
    dispatches0 = registry.counter("fused_batch_dispatches").value
    merged0 = registry.counter("fused_batch_merged_panels").value
    got = engine.query_range_batch(PANELS, *args)
    assert registry.counter("fused_batch_merged_panels").value - merged0 \
        >= 4, "sum/avg/count panels did not merge"
    # 6 panels, at most two dispatches: one group-mode (sum/avg/count and
    # ragged counts merged via disjoint-id multi-hot), one per-series
    # mode shared by min/max
    assert registry.counter("fused_batch_dispatches").value - dispatches0 \
        <= 2
    for q, w, g in zip(PANELS, want, got):
        g = _series_map(g)
        assert set(g) == set(w), q
        for k in w:
            np.testing.assert_allclose(g[k], w[k], rtol=2e-5, atol=1e-4,
                                       equal_nan=True, err_msg=q)


def test_batch_mixed_eligibility(fused_env):
    """Non-fusable and erroring queries ride along untouched."""
    engine = _mk()
    args = (START_S + 600, 60, END_S)
    queries = [PANELS[0],
               'rate(request_total{_ws_="demo"}[5m])',      # no agg: general
               'sum(nosuch_metric[5m])',                    # parse error
               'topk(2, rate(request_total{_ws_="demo"}[5m]))',  # candidate
               PANELS[1]]
    got = engine.query_range_batch(queries, *args)
    assert got[2].error is not None
    for i in (0, 1, 3, 4):
        w = _series_map(engine.query_range(queries[i], *args))
        g = _series_map(got[i])
        assert set(g) == set(w), queries[i]
        for k in w:
            np.testing.assert_allclose(g[k], w[k], rtol=2e-5, atol=1e-4,
                                       equal_nan=True, err_msg=queries[i])


def test_batch_general_path_without_fused(monkeypatch):
    """With the fused kernel unavailable (no TPU, interpret off), the
    batch API still answers every query via the general path."""
    monkeypatch.delenv("FILODB_TPU_FUSED_INTERPRET", raising=False)
    engine = _mk()
    args = (START_S + 600, 60, END_S)
    got = engine.query_range_batch(PANELS[:3], *args)
    for q, g in zip(PANELS[:3], got):
        w = _series_map(engine.query_range(q, *args))
        g = _series_map(g)
        assert set(g) == set(w)
        for k in w:
            np.testing.assert_allclose(g[k], w[k], rtol=1e-6,
                                       equal_nan=True)


def test_batch_ragged_matches_individual_queries(fused_env):
    """NaN scrape gaps (the production-normal shape): the merged ragged
    dispatch — multi-hot presence epilogue + disjoint-offset counts
    slicing in fused_leaf_agg_batch — must match per-query results."""
    from filodb_tpu.core.records import RecordBatch
    batch = counter_batch(48, T, start_ms=START_MS)
    vals = batch.columns["count"].copy()
    rng = np.random.default_rng(11)
    vals[rng.random(vals.shape) < 0.1] = np.nan      # scrape gaps
    batch = RecordBatch(batch.schema, batch.part_keys, batch.part_idx,
                        batch.timestamps, {"count": vals},
                        batch.bucket_les)
    engine = _mk([batch])
    args = (START_S + 600, 60, END_S)
    want = [_series_map(engine.query_range(q, *args)) for q in PANELS]
    merged0 = registry.counter("fused_batch_merged_panels").value
    got = engine.query_range_batch(PANELS, *args)
    assert registry.counter("fused_batch_merged_panels").value - merged0 \
        >= 4, "ragged panels did not merge"
    for q, w, g in zip(PANELS, want, got):
        g = _series_map(g)
        assert set(g) == set(w), q
        for k in w:
            np.testing.assert_allclose(g[k], w[k], rtol=2e-5, atol=1e-4,
                                       equal_nan=True, err_msg=q)


def test_batch_multi_shard(fused_env):
    """Two shards: each shard's leaves merge within their own working
    set (different mirrors -> different compat keys), and the stitched
    results still match individual queries."""
    engine = _mk_engine([counter_batch(60, T, start_ms=START_MS,
                                       resets=True)], num_shards=2)
    args = (START_S + 600, 60, END_S)
    queries = PANELS[:4]
    want = [_series_map(engine.query_range(q, *args)) for q in queries]
    merged0 = registry.counter("fused_batch_merged_panels").value
    got = engine.query_range_batch(queries, *args)
    # 4 panels x 2 shard-leaves each: both shards' sets merge
    assert registry.counter("fused_batch_merged_panels").value - merged0 \
        >= 6, "per-shard leaf sets did not merge"
    for q, w, g in zip(queries, want, got):
        g = _series_map(g)
        assert set(g) == set(w), q
        for k in w:
            np.testing.assert_allclose(g[k], w[k], rtol=2e-5, atol=1e-4,
                                       equal_nan=True, err_msg=q)


def test_coalescer_merges_concurrent_queries(fused_env):
    """Server-side micro-batching: concurrent query_range calls over one
    window grid coalesce into a single engine batch with per-query
    results identical to direct execution."""
    import threading

    from filodb_tpu.query.coalesce import QueryCoalescer
    engine = _mk()
    args = (START_S + 600, 60, END_S)
    for q in PANELS[:4]:
        assert engine.query_range(q, *args).error is None   # warm mirror
    co = QueryCoalescer(engine, window_s=0.25)
    merged0 = registry.counter("fused_batch_merged_panels").value
    results = {}
    errors = []

    def call(q):
        try:
            results[q] = _series_map(co.query_range(q, *args))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=call, args=(q,))
               for q in PANELS[:4]]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert registry.counter("fused_batch_merged_panels").value - merged0 \
        >= 3, "concurrent queries did not coalesce"
    for q in PANELS[:4]:
        want = _series_map(engine.query_range(q, *args))
        assert set(results[q]) == set(want), q
        for k in want:
            np.testing.assert_allclose(results[q][k], want[k], rtol=2e-5,
                                       atol=1e-4, equal_nan=True,
                                       err_msg=q)


def test_coalescer_window_zero_is_passthrough(fused_env):
    from filodb_tpu.query.coalesce import QueryCoalescer
    engine = _mk()
    args = (START_S + 600, 60, END_S)
    co = QueryCoalescer(engine, window_s=0.0)
    got = _series_map(co.query_range(PANELS[0], *args))
    want = _series_map(engine.query_range(PANELS[0], *args))
    assert set(got) == set(want)


def test_coalescer_failed_batch_falls_back(fused_env, monkeypatch):
    """A batch-path failure must not lose queries that succeed alone."""
    from filodb_tpu.query.coalesce import QueryCoalescer
    engine = _mk()
    args = (START_S + 600, 60, END_S)

    def boom(*a, **k):
        raise RuntimeError("batch path down")

    monkeypatch.setattr(engine, "query_range_batch", boom)
    co = QueryCoalescer(engine, window_s=0.05)
    res = co.query_range(PANELS[0], *args)
    assert res.error is None
    assert _series_map(res)


def test_batch_histogram_quantile_dashboard(fused_env):
    """The canonical quantile dashboard: p50/p90/p99 panels over ONE
    bucket metric differ only above the leaf, so their leaf calls dedup
    to a single kernel run; a differently-grouped hist panel merges via
    slot offsets.  All results equal individual queries."""
    from filodb_tpu.ingest.generator import histogram_batch
    engine = _mk_engine([histogram_batch(24, T, start_ms=START_MS)])
    args = (START_S + 600, 60, END_S)
    panels = [
        'histogram_quantile(0.5, sum(rate(http_latency{_ws_="demo"}[5m])))',
        'histogram_quantile(0.9, sum(rate(http_latency{_ws_="demo"}[5m])))',
        'histogram_quantile(0.99, sum(rate(http_latency{_ws_="demo"}[5m])))',
        'histogram_quantile(0.9, '
        'sum(rate(http_latency{_ws_="demo"}[5m])) by (_ns_))',
    ]
    want = [_series_map(engine.query_range(q, *args)) for q in panels]
    dedup0 = registry.counter("fused_batch_deduped").value
    got = engine.query_range_batch(panels, *args)
    assert registry.counter("fused_batch_deduped").value - dedup0 >= 2, \
        "identical quantile-panel leaves did not dedup"
    for q, w, g in zip(panels, want, got):
        g = _series_map(g)
        assert set(g) == set(w), q
        for k in w:
            np.testing.assert_allclose(g[k], w[k], rtol=2e-5, atol=1e-4,
                                       equal_nan=True, err_msg=q)


def test_coalescer_separates_planner_params(fused_env):
    """Requests with different planner params (limits, spread) must land
    in separate coalescing groups — sharing a batch across them would
    apply one request's limits to another's query."""
    import threading

    from filodb_tpu.query.coalesce import QueryCoalescer
    from filodb_tpu.query.rangevector import PlannerParams
    engine = _mk()
    args = (START_S + 600, 60, END_S)
    engine.query_range(PANELS[0], *args)            # warm mirror
    co = QueryCoalescer(engine, window_s=0.2)
    results = {}

    def call(tag, pp):
        results[tag] = co.query_range(PANELS[0], *args, pp)

    tight = PlannerParams(sample_limit=1)           # must error
    loose = PlannerParams()
    ts = [threading.Thread(target=call, args=("tight", tight)),
          threading.Thread(target=call, args=("loose", loose))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert results["loose"].error is None
    assert results["tight"].error is not None \
        and "limit" in results["tight"].error
