"""Bitmap posting-engine parity + churn suite (core/postings.py,
core/index.py rewrite).

The rewritten PartKeyIndex must be OBSERVABLY identical to the
sorted-array engine it replaced: same ids, same order (endTime-stable),
same ""-absent semantics, same metadata walks.  The old engine rides
along below as `OracleIndex` (verbatim from the pre-bitmap index.py)
and a seeded fuzz drives both through the same add / evict /
end-time-update / compact / query schedule, comparing every answer.

Divergence contract (the ONLY allowed differences, all from lazy vs
eager deletion):
  - pre-compaction both engines keep emptied values/labels in their
    dicts, so no-filter walks match exactly;
  - after the bitmap engine compacts, it prunes dead values AND dead
    labels (the "label_names lists dead labels" fix) while the oracle
    keeps empty entries forever — so post-compaction the bitmap walks
    must equal the oracle's walks filtered to non-empty postings, and
    stay a superset of those / subset of the oracle's full dict.
Everything id-shaped (part_ids_from_filters, ended_pids, counts>0) is
bit-identical always.
"""
import random
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pytest

from filodb_tpu.core.index import (ColumnFilter, Equals, EqualsRegex, In,
                                   MAX_TIME, NotEquals, NotEqualsRegex,
                                   NotIn, PartKeyIndex, Prefix)
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.utils.growable import grow_to


def _full_match(pattern: str, value: str) -> bool:
    return re.fullmatch(pattern, value) is not None


class OracleIndex:
    """The pre-bitmap PartKeyIndex (sorted numpy posting arrays, eager
    removal) — kept verbatim as the behavioral oracle."""

    def __init__(self):
        self._postings: Dict[str, Dict[str, List[int]]] = {}
        self._frozen: Dict[Tuple[str, str], np.ndarray] = {}
        self._having: Dict[str, np.ndarray] = {}
        self._start: np.ndarray = np.zeros(0, dtype=np.int64)
        self._end: np.ndarray = np.zeros(0, dtype=np.int64)
        self._alive: np.ndarray = np.zeros(0, dtype=bool)
        self._part_keys: List[Optional[PartKey]] = []
        self.num_docs = 0
        self.mutations = 0

    def add_partition(self, part_id: int, part_key: PartKey,
                      start_time_ms: int, end_time_ms: int = MAX_TIME) -> None:
        if part_id >= len(self._part_keys):
            n = part_id + 1
            self._start = grow_to(self._start, n)
            self._end = grow_to(self._end, n, fill=MAX_TIME)
            self._alive = grow_to(self._alive, n, fill=False)
            self._part_keys.extend(
                [None] * (self._start.shape[0] - len(self._part_keys)))
        self._part_keys[part_id] = part_key
        self._start[part_id] = start_time_ms
        self._end[part_id] = end_time_ms
        self._alive[part_id] = True
        self._index_label("__name__", part_key.metric, part_id)
        for k, v in part_key.tags:
            self._index_label(k, v, part_id)
        self.num_docs += 1
        self.mutations += 1

    def _index_label(self, key: str, value: str, part_id: int) -> None:
        self._postings.setdefault(key, {}).setdefault(value, []) \
            .append(part_id)
        self._frozen.pop((key, value), None)
        self._having.pop(key, None)

    def update_end_time(self, part_id: int, end_time_ms: int) -> None:
        self._end[part_id] = end_time_ms
        self.mutations += 1

    def start_time(self, part_id: int) -> int:
        return int(self._start[part_id])

    def end_time(self, part_id: int) -> int:
        return int(self._end[part_id])

    def part_key(self, part_id: int) -> Optional[PartKey]:
        return self._part_keys[part_id] \
            if part_id < len(self._part_keys) else None

    def _ids_for(self, key: str, value: str) -> np.ndarray:
        arr = self._frozen.get((key, value))
        if arr is None:
            lst = self._postings.get(key, {}).get(value, [])
            arr = np.asarray(lst, dtype=np.int64)
            self._frozen[(key, value)] = arr
        return arr

    def _all_ids(self) -> np.ndarray:
        return np.nonzero(self._alive)[0].astype(np.int64)

    def _union(self, parts) -> np.ndarray:
        parts = list(parts)
        return (np.unique(np.concatenate(parts)) if parts
                else np.zeros(0, dtype=np.int64))

    def _absent_or_empty(self, key: str) -> np.ndarray:
        having = self._having.get(key)
        if having is None:
            having = self._union(self._ids_for(key, v)
                                 for v in self._postings.get(key, {}) if v)
            self._having[key] = having
        return np.setdiff1d(self._all_ids(), having, assume_unique=False)

    def _match_filter(self, f: ColumnFilter) -> np.ndarray:
        key = "__name__" if f.column in ("__name__", "_metric_") \
            else f.column
        values = self._postings.get(key, {})
        if isinstance(f, Equals):
            return self._absent_or_empty(key) if f.value == "" \
                else self._ids_for(key, f.value)
        if isinstance(f, In):
            parts = [self._ids_for(key, v) for v in f.values if v]
            if "" in f.values:
                parts.append(self._absent_or_empty(key))
            return self._union(parts)
        if isinstance(f, Prefix):
            return self._union(self._ids_for(key, v) for v in values
                               if v.startswith(f.prefix))
        if isinstance(f, EqualsRegex):
            parts = [self._ids_for(key, v) for v in values
                     if v and _full_match(f.pattern, v)]
            if _full_match(f.pattern, ""):
                parts.append(self._absent_or_empty(key))
            return self._union(parts)
        if isinstance(f, (NotEquals, NotIn, NotEqualsRegex)):
            if isinstance(f, NotEquals):
                pos = Equals(f.column, f.value)
            elif isinstance(f, NotIn):
                pos = In(f.column, f.values)
            else:
                pos = EqualsRegex(f.column, f.pattern)
            return np.setdiff1d(self._all_ids(), self._match_filter(pos),
                                assume_unique=False)
        raise TypeError(f"unsupported filter {f!r}")

    def part_ids_from_filters(self, filters: Sequence[ColumnFilter],
                              start_time_ms: int, end_time_ms: int,
                              limit: Optional[int] = None) -> np.ndarray:
        ids: Optional[np.ndarray] = None
        for f in filters:
            cur = self._match_filter(f)
            ids = cur if ids is None \
                else np.intersect1d(ids, cur, assume_unique=False)
            if ids.size == 0:
                return ids
        if ids is None:
            ids = self._all_ids()
        mask = (self._start[ids] <= end_time_ms) \
            & (self._end[ids] >= start_time_ms)
        ids = ids[mask]
        ids = ids[np.argsort(self._end[ids], kind="stable")]
        return ids[:limit] if limit is not None else ids

    def label_values(self, label: str,
                     filters: Sequence[ColumnFilter] = (),
                     start_time_ms: int = 0, end_time_ms: int = MAX_TIME,
                     limit: Optional[int] = None) -> List[str]:
        key = "__name__" if label in ("__name__", "_metric_") else label
        if not filters:
            vals = sorted(self._postings.get(key, {}).keys())
            return vals[:limit] if limit else vals
        ids = set(self.part_ids_from_filters(
            filters, start_time_ms, end_time_ms).tolist())
        out = set()
        for value, plist in self._postings.get(key, {}).items():
            if not ids.isdisjoint(plist):
                out.add(value)
        vals = sorted(out)
        return vals[:limit] if limit else vals

    def label_value_counts(self, label: str) -> List[Tuple[str, int]]:
        key = "__name__" if label in ("__name__", "_metric_") else label
        out = [(v, len(plist))
               for v, plist in self._postings.get(key, {}).items()]
        return sorted(out, key=lambda kv: (-kv[1], kv[0]))

    def label_names(self, filters: Sequence[ColumnFilter] = (),
                    start_time_ms: int = 0,
                    end_time_ms: int = MAX_TIME) -> List[str]:
        if not filters:
            return sorted(self._postings.keys())
        ids = set(self.part_ids_from_filters(
            filters, start_time_ms, end_time_ms).tolist())
        out = set()
        for key, vals in self._postings.items():
            for plist in vals.values():
                if not ids.isdisjoint(plist):
                    out.add(key)
                    break
        return sorted(out)

    def ended_pids(self, before_ms: int) -> np.ndarray:
        n = len(self._part_keys)
        return np.flatnonzero(self._alive[:n] & (self._end[:n] < before_ms))

    def remove_partition(self, part_id: int) -> None:
        pk = self._part_keys[part_id]
        if pk is None:
            return
        for k, v in [("__name__", pk.metric)] + list(pk.tags):
            lst = self._postings.get(k, {}).get(v)
            if lst and part_id in lst:
                lst.remove(part_id)
                self._frozen.pop((k, v), None)
                self._having.pop(k, None)
        self._part_keys[part_id] = None
        self._alive[part_id] = False
        self.num_docs -= 1
        self.mutations += 1


# --------------------------------------------------------------- fuzz


METRICS = ["heap_usage", "req_total", "req_latency", "up", "gc_pause"]
WORKSPACES = ["demo", "prod", "stage"]
NAMESPACES = [f"App-{i}" for i in range(6)]
INSTANCES = [f"inst-{i:03d}" for i in range(25)]
JOBS = ["scraper", "api", "batch"]           # present on ~half the series


def _random_part_key(rng: random.Random) -> PartKey:
    tags = {
        "_ws_": rng.choice(WORKSPACES),
        "_ns_": rng.choice(NAMESPACES),
        "instance": rng.choice(INSTANCES),
    }
    if rng.random() < 0.5:                   # absent on the other half:
        tags["job"] = rng.choice(JOBS)       # exercises ""-semantics
    if rng.random() < 0.2:
        tags["path"] = f"/api/v{rng.randrange(3)}/x{rng.randrange(50)}"
    return PartKey.make(rng.choice(METRICS), tags)


def _filter_battery(rng: random.Random) -> List[List[ColumnFilter]]:
    """Every matcher shape the index supports, including the planner's
    edge cases: literal alternation, prefix extraction, trigram runs,
    empty-matching regexes, and patterns the planner must refuse to
    plan (lookahead) yet still answer correctly via full scan."""
    met = rng.choice(METRICS)
    ns = rng.choice(NAMESPACES)
    job = rng.choice(JOBS)
    inst = rng.choice(INSTANCES)
    return [
        [Equals("__name__", met)],
        [Equals("_metric_", met), Equals("_ns_", ns)],
        [Equals("job", job)],
        [Equals("job", "")],                       # absent-or-empty
        [Equals("_ns_", "no-such-ns")],
        [NotEquals("job", job)],
        [NotEquals("job", "")],                    # "has a job label"
        [In("_ns_", (ns, rng.choice(NAMESPACES)))],
        [In("job", ("", job))],
        [NotIn("_ns_", (ns,))],
        [Prefix("instance", inst[:6])],
        [Prefix("_ns_", "App")],
        [Prefix("_ns_", "zzz")],
        [EqualsRegex("_ns_", f"{ns}|App-0")],      # literal alternation
        [EqualsRegex("instance", "inst-0.*")],     # literal prefix
        [EqualsRegex("instance", ".*-01.*")],      # trigram runs
        [EqualsRegex("job", f"({job})?")],         # matches "" -> absent
        [EqualsRegex("_ns_", "App-[0-3]")],        # class: scan fallback
        [EqualsRegex("job", "(?=s).*")],           # lookahead: no plan
        [EqualsRegex("path", ".*")],               # match-all incl absent
        [NotEqualsRegex("_ns_", f"{ns}|App-1")],
        [Equals("__name__", met), NotEqualsRegex("job", ".+")],
        [Equals("_ws_", rng.choice(WORKSPACES)),
         EqualsRegex("_ns_", "App-.*"),
         NotEquals("instance", inst)],
    ]


def _assert_walk_parity(new: PartKeyIndex, oracle: OracleIndex,
                        compacted: bool) -> None:
    labels = set(oracle._postings) | set(new.label_names())
    for label in labels:
        o_all = oracle._postings.get(label, {})
        o_live = {v for v, lst in o_all.items() if lst}
        n_vals = set(new.label_values(label))
        if not compacted:
            assert n_vals == set(o_all), label
        else:
            assert o_live <= n_vals <= set(o_all), label
        # counts: identical for every value still holding live series,
        # in identical (-count, value) order over the >0 prefix
        o_counts = [kv for kv in oracle.label_value_counts(label)
                    if kv[1] > 0]
        n_counts = [kv for kv in new.label_value_counts(label) if kv[1] > 0]
        assert n_counts == o_counts, label
    o_names = set(oracle.label_names())
    o_live_names = {k for k, vals in oracle._postings.items()
                    if any(vals.values())}
    n_names = set(new.label_names())
    if not compacted:
        assert n_names == o_names
    else:
        assert o_live_names <= n_names <= o_names


def _assert_parity(new: PartKeyIndex, oracle: OracleIndex,
                   rng: random.Random, compacted: bool) -> None:
    assert new.num_docs == oracle.num_docs
    windows = [(0, MAX_TIME), (0, 5_000_000), (2_000_000, MAX_TIME),
               (1_500_000, 3_500_000)]
    for filters in _filter_battery(rng):
        s, e = rng.choice(windows)
        limit = rng.choice([None, None, 1, 7])
        got = new.part_ids_from_filters(filters, s, e, limit=limit)
        want = oracle.part_ids_from_filters(filters, s, e, limit=limit)
        assert np.array_equal(got, want), (filters, s, e, limit)
        # filtered metadata walks ride the same id sets: exact always
        lbl = rng.choice(["_ns_", "job", "__name__", "instance"])
        assert new.label_values(lbl, filters, s, e) \
            == oracle.label_values(lbl, filters, s, e), (lbl, filters)
        assert new.label_names(filters, s, e) \
            == oracle.label_names(filters, s, e), filters
    for cutoff in (0, 2_000_000, MAX_TIME):
        assert np.array_equal(new.ended_pids(cutoff),
                              oracle.ended_pids(cutoff))
    alive = oracle._all_ids()
    for pid in rng.sample(alive.tolist(), min(10, alive.size)):
        assert new.start_time(pid) == oracle.start_time(pid)
        assert new.end_time(pid) == oracle.end_time(pid)
        assert new.part_key(pid) == oracle.part_key(pid)
    _assert_walk_parity(new, oracle, compacted)


@pytest.mark.parametrize("seed", [7, 1234])
def test_fuzz_parity_with_sorted_array_oracle(seed):
    rng = random.Random(seed)
    new, oracle = PartKeyIndex(), OracleIndex()
    alive_pids: List[int] = []
    next_pid = 0
    # seed population
    for _ in range(400):
        pk = _random_part_key(rng)
        start = rng.randrange(1_000_000, 4_000_000)
        new.add_partition(next_pid, pk, start)
        oracle.add_partition(next_pid, pk, start)
        alive_pids.append(next_pid)
        next_pid += 1
    _assert_parity(new, oracle, rng, compacted=False)
    compacted = False
    for step in range(6):
        for _ in range(120):
            op = rng.random()
            if op < 0.45 or not alive_pids:
                pk = _random_part_key(rng)
                start = rng.randrange(1_000_000, 4_000_000)
                new.add_partition(next_pid, pk, start)
                oracle.add_partition(next_pid, pk, start)
                alive_pids.append(next_pid)
                next_pid += 1
            elif op < 0.75:
                pid = alive_pids.pop(rng.randrange(len(alive_pids)))
                new.remove_partition(pid)
                oracle.remove_partition(pid)
            else:
                pid = rng.choice(alive_pids)
                end = rng.randrange(1_500_000, 5_000_000)
                new.update_end_time(pid, end)
                oracle.update_end_time(pid, end)
        if step % 2 == 1:
            stats = new.compact()
            assert new.tombstone_count == 0
            assert stats["tombstones_pruned"] >= 0
            compacted = True
        _assert_parity(new, oracle, rng, compacted=compacted)


def test_pid_reuse_after_tombstone():
    """A pid evicted then reassigned to a DIFFERENT key before any
    compaction ran (flush/recovery reassigns pids densely) must shed its
    old postings — the lazy tombstone cannot leak the old key's bits
    into the new key's lookups."""
    new, oracle = PartKeyIndex(), OracleIndex()
    a = PartKey.make("m", {"_ws_": "w", "_ns_": "n1"})
    b = PartKey.make("m", {"_ws_": "w", "_ns_": "n2"})
    for idx in (new, oracle):
        idx.add_partition(0, a, 1000)
        idx.remove_partition(0)
        idx.add_partition(0, b, 2000)
    for f in ([Equals("_ns_", "n1")], [Equals("_ns_", "n2")]):
        assert np.array_equal(
            new.part_ids_from_filters(f, 0, MAX_TIME),
            oracle.part_ids_from_filters(f, 0, MAX_TIME)), f


def test_dead_labels_pruned_after_compaction():
    """Satellite: a label carried only by evicted series must vanish
    from label_names() once compaction runs (the old engine listed dead
    labels forever)."""
    idx = PartKeyIndex()
    keep = PartKey.make("m", {"_ws_": "w", "common": "x"})
    churn = PartKey.make("m", {"_ws_": "w", "ephemeral": "y"})
    idx.add_partition(0, keep, 1000)
    idx.add_partition(1, churn, 1000)
    assert "ephemeral" in idx.label_names()
    idx.remove_partition(1)
    idx.compact()
    assert "ephemeral" not in idx.label_names()
    assert "common" in idx.label_names()
    assert idx.label_values("ephemeral") == []


def test_churn_compaction_reclaims_memory():
    """3x-shard-size churn soak in miniature: evict-all / refill cycles
    with ever-increasing pids.  Compaction must purge every tombstone
    and rebase fully-dead leading containers, holding memory_bytes()
    flat instead of growing with lifetime pid count."""
    idx = PartKeyIndex()
    n_per_cycle = 70_000         # > one 65536-pid container per cycle
    next_pid = 0
    sizes = []
    for cycle in range(3):
        pids = []
        for i in range(n_per_cycle):
            pk = PartKey.make(
                "m", {"_ws_": "w", "_ns_": f"ns-{i % 40}",
                      "instance": f"i{i % 997}"})
            idx.add_partition(next_pid, pk, 1000)
            pids.append(next_pid)
            next_pid += 1
        assert idx.num_docs == n_per_cycle
        sizes.append(idx.memory_bytes())   # full-shard footprint per gen
        if cycle < 2:
            for pid in pids:
                idx.remove_partition(pid)
            assert idx.tombstone_count == n_per_cycle
            stats = idx.compact()
            assert idx.tombstone_count == 0
            assert stats["tombstones_pruned"] == n_per_cycle
            assert stats["ids_rebased"] >= 65536   # container rebase ran
    # steady state: a full shard after 3 churn generations costs no more
    # than +10% over the first generation
    assert sizes[-1] <= sizes[0] * 1.10, sizes
    # and queries on the rebased id space still resolve
    ids = idx.part_ids_from_filters([Equals("_ns_", "ns-7")], 0, MAX_TIME)
    assert ids.size == n_per_cycle // 40
    assert int(ids.min()) >= 2 * n_per_cycle


def test_bitmap_array_vs_container_mode_parity():
    """The Bitmap's two representations (array mode below SMALL_MAX,
    containers above) must agree on every operation.  The index-level
    fuzz universe is small enough to stay in array mode throughout, so
    this drives the container algebra directly by force-converting one
    side of each pair."""
    from filodb_tpu.core.postings import Bitmap, union_many

    rng = np.random.default_rng(99)

    def make_pair(ids):
        a, b = Bitmap(), Bitmap()
        for pid in ids:
            a.add(int(pid))
            b.add(int(pid))
        b._to_containers()          # force the container representation
        return a, b

    for trial in range(20):
        span = int(rng.integers(1 << 16, 1 << 21))
        n = int(rng.integers(1, 3000))
        ids = rng.choice(span, size=n, replace=False)
        a, b = make_pair(ids)
        assert np.array_equal(a.to_array(), b.to_array())
        assert a.cardinality() == b.cardinality()
        probes = rng.integers(0, span, size=50)
        for p in probes.tolist():
            assert a.contains(p) == b.contains(p)
        # removal keeps both sides aligned
        dead = rng.choice(ids, size=n // 3, replace=False) \
            if n >= 3 else ids[:0]
        a.remove_many(dead.astype(np.int64))
        b.remove_many(dead.astype(np.int64))
        assert np.array_equal(a.to_array(), b.to_array())
        one = int(ids[0])
        a.discard(one)
        b.discard(one)
        assert np.array_equal(a.to_array(), b.to_array())
        # cross-mode algebra: intersects / intersection_cardinality
        other_ids = rng.choice(span, size=max(1, n // 2), replace=False)
        oa, ob = make_pair(other_ids)
        want = np.intersect1d(a.to_array(), oa.to_array()).size
        for x in (a, b):
            for y in (oa, ob):
                assert x.intersection_cardinality(y) == want
                assert x.intersects(y) == (want > 0)
        # unions across mixed modes agree with the set union
        exp = np.union1d(a.to_array(), oa.to_array())
        for combo in ([a, oa], [a, ob], [b, oa], [b, ob]):
            assert np.array_equal(union_many(combo).to_array(), exp)


def test_bitmap_array_mode_converts_past_threshold():
    from filodb_tpu.core.postings import SMALL_MAX, Bitmap
    bm = Bitmap()
    for pid in range(0, (SMALL_MAX + 10) * 7, 7):   # spread over ids
        bm.add(pid)
    assert not bm._is_small()                       # flipped to containers
    assert bm.cardinality() == SMALL_MAX + 10
    assert bm.contains(7) and not bm.contains(8)


def test_maybe_compact_threshold():
    idx = PartKeyIndex()
    for i in range(10):
        idx.add_partition(i, PartKey.make("m", {"_ws_": "w", "i": str(i)}),
                          1000)
    for i in range(4):
        idx.remove_partition(i)
    assert not idx.maybe_compact(5)      # backlog 4 < threshold 5
    assert idx.tombstone_count == 4
    assert idx.maybe_compact(4)          # backlog 4 >= threshold 4
    assert idx.tombstone_count == 0


# ------------------------------------------- tenant cardinality budget


def _shard_with_limit(limit: int):
    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    cfg = FilodbSettings()
    cfg.index.tenant_series_limit = limit
    ms = TimeSeriesMemStore(config=cfg)
    return ms.setup("prometheus", 0)


def test_tenant_budget_rejects_over_limit():
    from filodb_tpu.core.ratelimit import (QuotaReachedException,
                                           TenantBudgetExceeded)
    shard = _shard_with_limit(3)
    for i in range(3):
        shard.get_or_create_partition(
            PartKey.make("m", {"_ws_": "noisy", "_ns_": "n",
                               "i": str(i)}), "gauge", 1_000_000)
    with pytest.raises(TenantBudgetExceeded) as exc:
        shard.get_or_create_partition(
            PartKey.make("m", {"_ws_": "noisy", "_ns_": "n", "i": "3"}),
            "gauge", 1_000_000)
    # structured: drop sites catch QuotaReachedException
    assert isinstance(exc.value, QuotaReachedException)
    assert exc.value.ws == "noisy" and exc.value.quota == 3
    assert shard.stats.tenant_rejected == 1
    # an existing series re-resolves fine at the limit
    shard.get_or_create_partition(
        PartKey.make("m", {"_ws_": "noisy", "_ns_": "n", "i": "0"}),
        "gauge", 1_000_000)
    # other tenants are unaffected
    shard.get_or_create_partition(
        PartKey.make("m", {"_ws_": "quiet", "_ns_": "n", "i": "0"}),
        "gauge", 1_000_000)
    assert shard.stats.tenant_rejected == 1


def test_tenant_budget_exemptions():
    """_rules_/_self_ (internal recording/selfmon series) and series
    without a _ws_ tag are never budget-limited."""
    shard = _shard_with_limit(2)
    for ws in ("_rules_", "_self_"):
        for i in range(5):
            shard.get_or_create_partition(
                PartKey.make("m", {"_ws_": ws, "_ns_": "n", "i": str(i)}),
                "gauge", 1_000_000)
    for i in range(5):
        shard.get_or_create_partition(
            PartKey.make("m", {"i": str(i)}), "gauge", 1_000_000)
    assert shard.stats.tenant_rejected == 0


def test_tenant_budget_freed_by_eviction():
    from filodb_tpu.core.ratelimit import TenantBudgetExceeded
    shard = _shard_with_limit(2)
    for i in range(2):
        shard.get_or_create_partition(
            PartKey.make("m", {"_ws_": "w", "_ns_": "n", "i": str(i)}),
            "gauge", 1_000_000)
    with pytest.raises(TenantBudgetExceeded):
        shard.get_or_create_partition(
            PartKey.make("m", {"_ws_": "w", "_ns_": "n", "i": "2"}),
            "gauge", 1_000_000)
    for pid in range(2):
        shard.index.update_end_time(pid, 1_050_000)
    assert shard.evict_ended_partitions(2_000_000) == 2
    # eviction returned the budget: the tenant can create again
    shard.get_or_create_partition(
        PartKey.make("m", {"_ws_": "w", "_ns_": "n", "i": "2"}),
        "gauge", 3_000_000)


def test_status_tsdb_endpoint():
    """GET /api/v1/status/tsdb: Prometheus-compatible head stats with
    the tenant table and budget-rejection counter folded in."""
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    from filodb_tpu.config import FilodbSettings
    cfg = FilodbSettings()
    cfg.index.tenant_series_limit = 4
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     http_port=0, config=cfg)
    try:
        shard = srv.memstore.get_shard("prometheus", 0)
        for i in range(4):
            shard.get_or_create_partition(
                PartKey.make("heap_usage",
                             {"_ws_": "demo", "_ns_": "n", "i": str(i)}),
                "gauge", 1_000_000)
        from filodb_tpu.core.ratelimit import TenantBudgetExceeded
        with pytest.raises(TenantBudgetExceeded):
            shard.get_or_create_partition(
                PartKey.make("heap_usage",
                             {"_ws_": "demo", "_ns_": "n", "i": "4"}),
                "gauge", 1_000_000)
        st, payload = srv.api.handle(
            "GET", "/api/v1/status/tsdb", {"limit": "5"})
        assert st == 200 and payload["status"] == "success"
        data = payload["data"]
        head = data["headStats"]
        assert head["numSeries"] == 4
        assert head["tenantSeriesLimit"] == 4
        assert head["tenantSeriesRejected"] == 1
        tenants = {r["name"]: r["value"]
                   for r in data["seriesCountByTenant"]}
        assert tenants == {"demo": 4}
        metrics = {r["name"]: r["value"]
                   for r in data["seriesCountByMetricName"]}
        assert metrics == {"heap_usage": 4}
        assert any(r["name"] == "_ws_=demo"
                   for r in data["seriesCountByLabelValuePair"])
        assert all(r["value"] > 0
                   for r in data["memoryInBytesByLabelName"])
    finally:
        srv.shutdown()
