"""Strict Prometheus text-exposition validation of /metrics (tier-1).

A malformed exposition must never ship: Prometheus silently drops bad
scrape bodies, which reads as "the server is fine" while every alert
goes dark.  This parses EVERY line of the live registry's output —
including metrics other tests seeded — against the exposition grammar:
metric-name regex, fully-escaped label values, monotone non-decreasing
bucket counts with ascending `le` bounds, and `_sum`/`_count`
consistency per histogram family.
"""
import math
import re

import numpy as np

from filodb_tpu.utils.metrics import Histogram, registry

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_VALUE_RE = re.compile(
    r"^(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[-+]?Inf|NaN)$")
# one label pair: name="value" with only \\ \" \n escapes inside
_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|\\n)*)"(?:,|$)')


def _parse_line(line):
    """(name, labels_dict, value) or raise AssertionError."""
    m = re.match(r"^([^{ ]+)(\{(.*)\})? (.+)$", line)
    assert m, f"unparsable exposition line: {line!r}"
    name, _, labels_raw, value = m.groups()
    assert _NAME_RE.match(name), f"bad metric name: {name!r}"
    labels = {}
    if labels_raw:
        pos = 0
        while pos < len(labels_raw):
            pm = _PAIR_RE.match(labels_raw, pos)
            assert pm, (f"bad label syntax at {labels_raw[pos:]!r} "
                        f"in: {line!r}")
            assert _LABEL_NAME_RE.match(pm.group(1))
            labels[pm.group(1)] = pm.group(2)
            pos = pm.end()
    assert _VALUE_RE.match(value), f"bad sample value {value!r} in {line!r}"
    return name, labels, float(value.replace("Inf", "inf")
                               .replace("NaN", "nan"))


def _strict_parse(text):
    """Parse a full exposition body; returns {(name, frozen_labels): value}
    and the per-histogram family structures for consistency checks."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, labels, value = _parse_line(line)
        key = (name, tuple(sorted(labels.items())))
        assert key not in samples, f"duplicate sample: {key}"
        samples[key] = value
    return samples


def _histogram_families(samples):
    """{(base, labels-without-le): {"buckets": [(le, v)], "sum", "count"}}"""
    fams = {}
    for (name, labels), value in samples.items():
        if name.endswith("_bucket"):
            base = name[:-len("_bucket")]
            lab = dict(labels)
            le = lab.pop("le")
            fam = fams.setdefault((base, tuple(sorted(lab.items()))), {})
            fam.setdefault("buckets", []).append((le, value))
        elif name.endswith("_sum") and (name[:-4], labels) not in samples:
            # a histogram's _sum (counters end _total, gauges are bare)
            fams.setdefault((name[:-4], labels), {})["sum"] = value
        elif name.endswith("_count"):
            fams.setdefault((name[:-6], labels), {})["count"] = value
    return {k: v for k, v in fams.items() if "buckets" in v}


def test_metrics_exposition_is_strictly_parseable():
    # seed nasty label values: the escaping satellite's regression net
    registry.counter("expo_strict_ops",
                     path='a"b\\c\nd', ok="yes").increment(3)
    registry.gauge("expo_strict_depth", unit="ms").update(-1.5)
    h = registry.histogram("expo_strict_lat", route="/x")
    for v in (0.002, 0.04, 7.0, 1e9):      # incl. overflow bucket
        h.record(v)
    text = registry.expose_prometheus()
    samples = _strict_parse(text)
    # the escaped label round-trips: unescape recovers the original
    esc = [v for (n, labels), v in samples.items()
           if n == "expo_strict_ops_total" and dict(labels).get("ok") == "yes"]
    assert len(esc) == 1
    raw = [dict(labels)["path"] for (n, labels) in samples
           if n == "expo_strict_ops_total"][0]
    assert raw.replace("\\\\", "\x00").replace('\\"', '"') \
        .replace("\\n", "\n").replace("\x00", "\\") == 'a"b\\c\nd'

    fams = _histogram_families(samples)
    assert ("expo_strict_lat", (("route", "/x"),)) in fams
    for (base, labels), fam in fams.items():
        where = f"{base}{dict(labels)}"
        assert "sum" in fam, f"{where}: missing _sum"
        assert "count" in fam, f"{where}: missing _count"
        # le bounds ascending with +Inf last; cumulative counts monotone
        les = [le for le, _ in fam["buckets"]]
        assert les.count("+Inf") == 1 and les[-1] == "+Inf", where
        bounds = [float(le) for le in les[:-1]]
        assert bounds == sorted(bounds), f"{where}: le not ascending"
        counts = [v for _, v in fam["buckets"]]
        assert all(b >= a for a, b in zip(counts, counts[1:])), \
            f"{where}: bucket counts not monotone"
        assert counts[-1] == fam["count"], \
            f"{where}: +Inf bucket != _count"
        assert math.isfinite(fam["sum"]), where


def test_exposition_survives_concurrent_histogram_writes():
    """The expose-vs-record race (satellite 1): a scrape formatting a
    histogram mid-record must never emit a cumulative bucket count above
    its _count.  Hammer one histogram from threads while scraping."""
    import threading

    h = registry.histogram("expo_race_lat")
    stop = threading.Event()

    def writer():
        rng = np.random.default_rng(0)
        while not stop.is_set():
            h.record(float(rng.random() * 10))

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(50):
            samples = _strict_parse(registry.expose_prometheus())
            fams = _histogram_families(samples)
            fam = fams.get(("expo_race_lat", ()))
            assert fam is not None
            counts = [v for _, v in fam["buckets"]]
            assert all(b >= a for a, b in zip(counts, counts[1:]))
            assert counts[-1] == fam["count"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


# ------------------------------------------------ openmetrics grammar

# OpenMetrics sample line: name{labels} value [# {exemplar} ev ets]
_OM_SAMPLE_RE = re.compile(
    r"^([^{ ]+)(\{(.*?)\})? (-?[0-9.]+(?:[eE][+-]?[0-9]+)?|[-+]?Inf|NaN)"
    r"( # \{trace_id=\"([^\"\\\n]*)\"\} (-?[0-9.]+(?:[eE][+-]?[0-9]+)?)"
    r" ([0-9.]+))?$")
# canonical float per the OpenMetrics ABNF: le values are floats,
# never bare ints
_OM_FLOAT_RE = re.compile(
    r"^(\+Inf|-?[0-9]+\.[0-9]+([eE][+-]?[0-9]+)?|-?[0-9.]+[eE][+-]?[0-9]+)$")


def _strict_parse_openmetrics(text):
    """Parse a full OpenMetrics body; returns (samples, types,
    exemplars) and asserts the grammar: `# TYPE` metadata precedes each
    family's samples, counters expose only `_total` under their family
    name, le values are canonical floats, exactly one `# EOF`
    terminator, nothing after it."""
    assert text.endswith("# EOF\n"), "missing the mandatory # EOF"
    body = text[:-len("# EOF\n")]
    assert "# EOF" not in body, "interior # EOF"
    samples, types, exemplars = {}, {}, {}
    for line in body.splitlines():
        assert line, "blank lines are not OpenMetrics"
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, kind = rest.partition(" ")
            assert _NAME_RE.match(fam), fam
            assert kind in ("counter", "gauge", "histogram"), line
            assert fam not in types, f"duplicate TYPE for {fam}"
            types[fam] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _OM_SAMPLE_RE.match(line)
        assert m, f"unparsable openmetrics line: {line!r}"
        name, _, labels_raw, value = m.group(1, 2, 3, 4)
        assert _NAME_RE.match(name), name
        labels = {}
        if labels_raw:
            pos = 0
            while pos < len(labels_raw):
                pm = _PAIR_RE.match(labels_raw, pos)
                assert pm, f"bad label syntax in {line!r}"
                labels[pm.group(1)] = pm.group(2)
                pos = pm.end()
        # metadata/sample-name contract: the sample belongs to a typed
        # family, under the kind's allowed suffixes
        fam = next((f for f in (name, name.rsplit("_", 1)[0])
                    if f in types), None)
        if name.endswith("_bucket"):
            fam = name[:-len("_bucket")]
        assert fam in types, f"sample {name!r} precedes its # TYPE"
        kind = types[fam]
        if kind == "counter":
            assert name == fam + "_total", \
                f"counter family {fam} exposes {name!r}"
        elif kind == "gauge":
            assert name == fam, f"gauge family {fam} exposes {name!r}"
        else:
            assert name in (fam + "_bucket", fam + "_sum",
                            fam + "_count"), \
                f"histogram family {fam} exposes {name!r}"
        if "le" in labels:
            assert _OM_FLOAT_RE.match(labels["le"]), \
                f"le not a canonical float: {labels['le']!r}"
        key = (name, tuple(sorted(labels.items())))
        assert key not in samples, f"duplicate sample: {key}"
        samples[key] = float(value.replace("Inf", "inf")
                             .replace("NaN", "nan"))
        if m.group(5):
            # exemplar only legal on histogram buckets; value/ts parse
            assert name.endswith("_bucket"), line
            exemplars[key] = (m.group(6), float(m.group(7)),
                              float(m.group(8)))
    return samples, types, exemplars


def test_openmetrics_exposition_strictly_parseable_with_exemplars():
    registry.counter("om_strict_ops", path='a"b\\c\nd').increment(2)
    registry.gauge("om_strict_depth").update(-2.5)
    h = registry.histogram("om_strict_lat", route="/om")
    h.record(0.003, exemplar="0123456789abcdef0123456789abcdef")
    h.record(42.0, exemplar="feedfacefeedfacefeedfacefeedface")
    text = registry.expose_openmetrics()
    samples, types, exemplars = _strict_parse_openmetrics(text)
    assert types["om_strict_ops"] == "counter"
    assert types["om_strict_depth"] == "gauge"
    assert types["om_strict_lat"] == "histogram"
    assert ("om_strict_ops_total", (("path", 'a\\"b\\\\c\\nd'),)) \
        in samples
    # the seeded exemplars ride their buckets
    got = {tid for (name, _), (tid, _v, _t) in exemplars.items()
           if name == "om_strict_lat_bucket"}
    assert {"0123456789abcdef0123456789abcdef",
            "feedfacefeedfacefeedfacefeedface"} <= got
    # exemplar values sit within their bucket's bound
    for (name, labels), (_tid, ev, ets) in exemplars.items():
        le = dict(labels).get("le")
        if le and le != "+Inf":
            assert ev <= float(le) + 1e-9, (labels, ev)
        assert ets > 1e9, "exemplar timestamp is unix seconds"
    # histogram family consistency holds in this grammar too
    fams = _histogram_families(samples)
    fam = fams[("om_strict_lat", (("route", "/om"),))]
    counts = [v for _, v in fam["buckets"]]
    assert all(b >= a for a, b in zip(counts, counts[1:]))
    assert counts[-1] == fam["count"] == 2


def test_openmetrics_survives_concurrent_scrapes():
    """The same expose-vs-record hammer as the Prometheus gate, on the
    OpenMetrics grammar — including exemplar writes racing the scrape."""
    import threading

    h = registry.histogram("om_race_lat")
    stop = threading.Event()

    def writer():
        rng = np.random.default_rng(1)
        i = 0
        while not stop.is_set():
            h.record(float(rng.random() * 10),
                     exemplar=f"{i:032x}")
            i += 1

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(25):
            samples, _types, _ex = _strict_parse_openmetrics(
                registry.expose_openmetrics())
            fams = _histogram_families(samples)
            fam = fams.get(("om_race_lat", ()))
            assert fam is not None
            counts = [v for _, v in fam["buckets"]]
            assert all(b >= a for a, b in zip(counts, counts[1:]))
            assert counts[-1] == fam["count"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_plain_exposition_unchanged_by_exemplars():
    """Exemplar-carrying histograms must leave the legacy format
    byte-free of metadata/exemplar syntax (the no-regression gate)."""
    h = registry.histogram("om_plain_lat")
    h.record(0.5, exemplar="aa" * 16)
    text = registry.expose_prometheus()
    assert "# " not in text and "# EOF" not in text
    # and still strictly parses under the legacy grammar
    _strict_parse(text)


def test_exemplars_toggle_off_drops_them():
    from filodb_tpu.utils.metrics import set_exemplars_enabled
    h = registry.histogram("om_toggle_lat")
    try:
        set_exemplars_enabled(False)
        h.record(0.1, exemplar="bb" * 16)
        assert not h.exemplars
    finally:
        set_exemplars_enabled(True)
    h.record(0.1, exemplar="cc" * 16)
    assert h.exemplars


def test_percentile_interpolates_and_estimates_overflow():
    h = Histogram(bounds=(1.0, 10.0))
    for _ in range(99):
        h.record(5.0)
    h.record(752.0)                      # the SOAK_LONG_r05 outlier shape
    # p50 interpolated inside (1, 10], not snapped to 10
    assert 1.0 < h.percentile(0.5) < 10.0
    # p100 reaches toward the observed max instead of capping at 10
    assert h.percentile(1.0) == 752.0
    # two histograms equal except their overflow magnitude now DIFFER
    h2 = Histogram(bounds=(1.0, 10.0))
    for _ in range(99):
        h2.record(5.0)
    h2.record(11.0)
    assert h.percentile(1.0) > h2.percentile(1.0)
