"""Strict Prometheus text-exposition validation of /metrics (tier-1).

A malformed exposition must never ship: Prometheus silently drops bad
scrape bodies, which reads as "the server is fine" while every alert
goes dark.  This parses EVERY line of the live registry's output —
including metrics other tests seeded — against the exposition grammar:
metric-name regex, fully-escaped label values, monotone non-decreasing
bucket counts with ascending `le` bounds, and `_sum`/`_count`
consistency per histogram family.
"""
import math
import re

import numpy as np

from filodb_tpu.utils.metrics import Histogram, registry

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_VALUE_RE = re.compile(
    r"^(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[-+]?Inf|NaN)$")
# one label pair: name="value" with only \\ \" \n escapes inside
_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|\\n)*)"(?:,|$)')


def _parse_line(line):
    """(name, labels_dict, value) or raise AssertionError."""
    m = re.match(r"^([^{ ]+)(\{(.*)\})? (.+)$", line)
    assert m, f"unparsable exposition line: {line!r}"
    name, _, labels_raw, value = m.groups()
    assert _NAME_RE.match(name), f"bad metric name: {name!r}"
    labels = {}
    if labels_raw:
        pos = 0
        while pos < len(labels_raw):
            pm = _PAIR_RE.match(labels_raw, pos)
            assert pm, (f"bad label syntax at {labels_raw[pos:]!r} "
                        f"in: {line!r}")
            assert _LABEL_NAME_RE.match(pm.group(1))
            labels[pm.group(1)] = pm.group(2)
            pos = pm.end()
    assert _VALUE_RE.match(value), f"bad sample value {value!r} in {line!r}"
    return name, labels, float(value.replace("Inf", "inf")
                               .replace("NaN", "nan"))


def _strict_parse(text):
    """Parse a full exposition body; returns {(name, frozen_labels): value}
    and the per-histogram family structures for consistency checks."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, labels, value = _parse_line(line)
        key = (name, tuple(sorted(labels.items())))
        assert key not in samples, f"duplicate sample: {key}"
        samples[key] = value
    return samples


def _histogram_families(samples):
    """{(base, labels-without-le): {"buckets": [(le, v)], "sum", "count"}}"""
    fams = {}
    for (name, labels), value in samples.items():
        if name.endswith("_bucket"):
            base = name[:-len("_bucket")]
            lab = dict(labels)
            le = lab.pop("le")
            fam = fams.setdefault((base, tuple(sorted(lab.items()))), {})
            fam.setdefault("buckets", []).append((le, value))
        elif name.endswith("_sum") and (name[:-4], labels) not in samples:
            # a histogram's _sum (counters end _total, gauges are bare)
            fams.setdefault((name[:-4], labels), {})["sum"] = value
        elif name.endswith("_count"):
            fams.setdefault((name[:-6], labels), {})["count"] = value
    return {k: v for k, v in fams.items() if "buckets" in v}


def test_metrics_exposition_is_strictly_parseable():
    # seed nasty label values: the escaping satellite's regression net
    registry.counter("expo_strict_ops",
                     path='a"b\\c\nd', ok="yes").increment(3)
    registry.gauge("expo_strict_depth", unit="ms").update(-1.5)
    h = registry.histogram("expo_strict_lat", route="/x")
    for v in (0.002, 0.04, 7.0, 1e9):      # incl. overflow bucket
        h.record(v)
    text = registry.expose_prometheus()
    samples = _strict_parse(text)
    # the escaped label round-trips: unescape recovers the original
    esc = [v for (n, labels), v in samples.items()
           if n == "expo_strict_ops_total" and dict(labels).get("ok") == "yes"]
    assert len(esc) == 1
    raw = [dict(labels)["path"] for (n, labels) in samples
           if n == "expo_strict_ops_total"][0]
    assert raw.replace("\\\\", "\x00").replace('\\"', '"') \
        .replace("\\n", "\n").replace("\x00", "\\") == 'a"b\\c\nd'

    fams = _histogram_families(samples)
    assert ("expo_strict_lat", (("route", "/x"),)) in fams
    for (base, labels), fam in fams.items():
        where = f"{base}{dict(labels)}"
        assert "sum" in fam, f"{where}: missing _sum"
        assert "count" in fam, f"{where}: missing _count"
        # le bounds ascending with +Inf last; cumulative counts monotone
        les = [le for le, _ in fam["buckets"]]
        assert les.count("+Inf") == 1 and les[-1] == "+Inf", where
        bounds = [float(le) for le in les[:-1]]
        assert bounds == sorted(bounds), f"{where}: le not ascending"
        counts = [v for _, v in fam["buckets"]]
        assert all(b >= a for a, b in zip(counts, counts[1:])), \
            f"{where}: bucket counts not monotone"
        assert counts[-1] == fam["count"], \
            f"{where}: +Inf bucket != _count"
        assert math.isfinite(fam["sum"]), where


def test_exposition_survives_concurrent_histogram_writes():
    """The expose-vs-record race (satellite 1): a scrape formatting a
    histogram mid-record must never emit a cumulative bucket count above
    its _count.  Hammer one histogram from threads while scraping."""
    import threading

    h = registry.histogram("expo_race_lat")
    stop = threading.Event()

    def writer():
        rng = np.random.default_rng(0)
        while not stop.is_set():
            h.record(float(rng.random() * 10))

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(50):
            samples = _strict_parse(registry.expose_prometheus())
            fams = _histogram_families(samples)
            fam = fams.get(("expo_race_lat", ()))
            assert fam is not None
            counts = [v for _, v in fam["buckets"]]
            assert all(b >= a for a, b in zip(counts, counts[1:]))
            assert counts[-1] == fam["count"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_percentile_interpolates_and_estimates_overflow():
    h = Histogram(bounds=(1.0, 10.0))
    for _ in range(99):
        h.record(5.0)
    h.record(752.0)                      # the SOAK_LONG_r05 outlier shape
    # p50 interpolated inside (1, 10], not snapped to 10
    assert 1.0 < h.percentile(0.5) < 10.0
    # p100 reaches toward the observed max instead of capping at 10
    assert h.percentile(1.0) == 752.0
    # two histograms equal except their overflow magnitude now DIFFER
    h2 = Histogram(bounds=(1.0, 10.0))
    for _ in range(99):
        h2.record(5.0)
    h2.record(11.0)
    assert h.percentile(1.0) > h2.percentile(1.0)
