"""Leaf-exec fused fast path: PeriodicSamplesMapper(rate) +
AggregateMapReduce(sum) collapsing into the Pallas kernel must be
transparent — same results as the general path, engaged only when the
mirror certifies the preconditions."""
import numpy as np
import pytest

from filodb_tpu.core.records import RecordBatch
from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.utils.metrics import registry

from test_query_engine import _mk_engine

START_MS = 1_600_000_000_000
START_S = START_MS // 1000
T = 240
END_S = START_S + T * 10


@pytest.fixture()
def fused_env(monkeypatch):
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")


def _fused_count():
    return registry.counter("leaf_fused_kernel").value + registry.counter("leaf_fused_count_host").value


def _query(engine, promql='sum(rate(request_total{_ws_="demo"}[5m])) by (_ns_)'):
    res = engine.query_range(promql, START_S + 600, 60, END_S)
    assert res.error is None, res.error
    return {tuple(sorted(k.labels_dict.items())): np.asarray(v)
            for k, _, v in res.series()}


def test_fused_leaf_matches_general_path(fused_env):
    batch = counter_batch(60, T, start_ms=START_MS, resets=True)
    engine = _mk_engine([batch])
    # warm the mirror; second query takes the fused path
    base = _query(engine)
    before = _fused_count()
    got = _query(engine)
    assert _fused_count() > before, "fused path did not engage"
    # general path, fused disabled
    import os
    os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
    want = _query(engine)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-5, atol=1e-4,
                                   equal_nan=True)
    for k in base:
        np.testing.assert_allclose(base[k], want[k], rtol=2e-5, atol=1e-4,
                                   equal_nan=True)


def test_fused_skipped_on_ragged_grid(fused_env):
    """Series with different sample grids must take the general path."""
    full = counter_batch(20, T, start_ms=START_MS)
    ragged = counter_batch(10, T // 2, start_ms=START_MS + 5_000,
                           metric="other_total", seed=5)
    engine = _mk_engine([full, ragged])
    before = _fused_count()
    a = _query(engine, 'sum(rate(request_total{_ws_="demo"}[5m])) by (_ns_)')
    b = _query(engine, 'sum(rate(other_total{_ws_="demo"}[5m])) by (_ns_)')
    assert _fused_count() == before     # mixed grids -> not uniform
    assert a and b


def test_fused_ragged_counter_engages_and_matches(fused_env):
    """NaN scrape gaps no longer disqualify the rate family (r4): the
    ragged kernel variant engages and matches the general path, which
    itself runs valid-boundary semantics on ragged data."""
    batch = counter_batch(8, T, start_ms=START_MS)
    vals = batch.columns["count"].copy()
    rng = np.random.default_rng(3)
    vals[rng.random(vals.shape) < 0.1] = np.nan      # scrape gaps
    batch = RecordBatch(batch.schema, batch.part_keys, batch.part_idx,
                        batch.timestamps, {"count": vals}, batch.bucket_les)
    engine = _mk_engine([batch])
    base = _query(engine)                # mirror warm-up
    before = _fused_count()
    got = _query(engine)
    assert _fused_count() > before, \
        "ragged counter should engage the fused kernel"
    import os
    os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
    want = _query(engine)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-5, atol=1e-4,
                                   equal_nan=True)


def test_fused_engages_after_incremental_append(fused_env):
    """Uniform appends preserve eligibility through the incremental
    mirror refresh."""
    full = counter_batch(30, T, start_ms=START_MS)
    k = full.timestamps < START_MS + (T - 40) * 10_000
    first = RecordBatch(full.schema, full.part_keys, full.part_idx[k],
                        full.timestamps[k],
                        {c: v[k] for c, v in full.columns.items()},
                        full.bucket_les)
    engine = _mk_engine([first])
    _query(engine)                       # mirror upload (full refresh)
    rest = RecordBatch(full.schema, full.part_keys, full.part_idx[~k],
                       full.timestamps[~k],
                       {c: v[~k] for c, v in full.columns.items()},
                       full.bucket_les)
    engine.source.get_shard("prometheus", 0).ingest(rest)
    _query(engine)                       # incremental refresh
    before = _fused_count()
    got = _query(engine)
    assert _fused_count() > before, \
        "uniform append should keep the fused path eligible"
    # equals a from-scratch engine over the full data
    fresh = _mk_engine([counter_batch(30, T, start_ms=START_MS)])
    want = _query(fresh)
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(got[key], want[key], rtol=2e-5,
                                   atol=1e-4, equal_nan=True)


def test_fused_prep_cache_reused_across_queries(fused_env):
    """Repeat queries over an unchanged snapshot must hit the prepared-input
    cache (no per-query full device pad) and still be correct."""
    engine = _mk_engine([counter_batch(40, T, start_ms=START_MS)])
    _query(engine)                       # mirror upload
    first = _query(engine)               # fused, cache miss
    hits0 = registry.counter("leaf_fused_prep_hits").value
    again = _query(engine)               # fused, cache hit
    assert registry.counter("leaf_fused_prep_hits").value > hits0
    for k in first:
        np.testing.assert_allclose(first[k], again[k], rtol=1e-6,
                                   equal_nan=True)


def test_fused_vals_cache_shared_across_groupings(fused_env):
    """Two grouping variants over one snapshot share ONE padded values
    copy (the grouping-dependent gid arrays are cached separately)."""
    from filodb_tpu.query import exec as exec_mod
    engine = _mk_engine([counter_batch(30, T, start_ms=START_MS)])
    _query(engine)                       # warm mirror
    exec_mod._FUSED_VALS_CACHE.clear()
    exec_mod._FUSED_GROUP_CACHE.clear()
    a = _query(engine, 'sum(rate(request_total{_ws_="demo"}[5m])) by (_ns_)')
    b = _query(engine, 'sum(rate(request_total{_ws_="demo"}[5m]))')
    assert len(exec_mod._FUSED_VALS_CACHE) == 1, \
        "grouping variants must share the padded values entry"
    assert len(exec_mod._FUSED_GROUP_CACHE) == 2
    assert a and b


def test_fused_histogram_sum_rate_matches_general(fused_env):
    """histogram sum(rate(bucket[5m])) through the fused kernel (bucket
    rows flattened into per-(group, bucket) slots) must match the general
    path, including downstream histogram_quantile."""
    from filodb_tpu.ingest.generator import histogram_batch
    engine = _mk_engine([histogram_batch(12, T, start_ms=START_MS)])
    q = ('histogram_quantile(0.9, '
         'sum(rate(http_latency{_ws_="demo"}[5m])) by (_ns_))')
    base = _query(engine, q)             # warm mirror
    before = _fused_count()
    got = _query(engine, q)
    assert _fused_count() > before, "hist fused path did not engage"
    import os
    os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
    want = _query(engine, q)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=5e-4, atol=1e-3,
                                   equal_nan=True)


@pytest.mark.parametrize("fn", ["sum_over_time", "avg_over_time"])
def test_fused_over_time_matches_general(fused_env, fn):
    """sum by of the *_over_time family through the band-matrix kernel
    must match the general path (gauge columns, vbase re-added)."""
    from filodb_tpu.ingest.generator import gauge_batch
    engine = _mk_engine([gauge_batch(40, T, start_ms=START_MS)])
    q = f'sum({fn}(heap_usage{{_ws_="demo"}}[5m])) by (_ns_)'
    base = _query(engine, q)             # warm mirror
    before = _fused_count()
    got = _query(engine, q)
    assert _fused_count() > before, f"{fn} fused path did not engage"
    import os
    os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
    want = _query(engine, q)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-4, atol=1e-3,
                                   equal_nan=True)


def test_fused_count_over_time_pure_host(fused_env):
    """sum by (count_over_time) over a shared dense grid is computed
    entirely host-side (gsize * n) and must match the general path."""
    from filodb_tpu.ingest.generator import gauge_batch
    engine = _mk_engine([gauge_batch(30, T, start_ms=START_MS)])
    q = 'sum(count_over_time(heap_usage{_ws_="demo"}[5m])) by (_ns_)'
    _query(engine, q)                    # warm mirror
    before = _fused_count()
    got = _query(engine, q)
    assert _fused_count() > before, "count_over_time fast path not used"
    import os
    os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
    want = _query(engine, q)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-9,
                                   equal_nan=True)


def test_fused_error_is_logged_with_reason(fused_env, caplog, monkeypatch):
    """A fused-path failure must leave a diagnosable warning (type +
    message), not just an anonymous error counter."""
    import logging

    from filodb_tpu.query import exec as exec_mod
    engine = _mk_engine([counter_batch(10, T, start_ms=START_MS)])
    _query(engine)                       # warm mirror

    def boom(*a, **k):
        raise RuntimeError("synthetic kernel failure")
    monkeypatch.setattr(exec_mod.MultiSchemaPartitionsExec,
                        "_try_fused",
                        lambda self, d, s: boom())
    from filodb_tpu.utils import metrics as metrics_mod
    metrics_mod._degrade_last.clear()
    with caplog.at_level(logging.WARNING, logger="filodb.fused"):
        got = _query(engine)             # degrades to general path
    assert got
    assert any("synthetic kernel failure" in r.message
               for r in caplog.records), caplog.records


# ------------------------- r3 broadened eligibility (VERDICT r2 item 2)

def _general_query(engine, q, monkeypatch):
    """Run q with the fused peephole disabled entirely."""
    from filodb_tpu.query import exec as exec_mod
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(exec_mod.MultiSchemaPartitionsExec, "_try_fused",
                   lambda self, d, s: None)
        return _query(engine, q)


def _fused_all():
    return (registry.counter("leaf_fused_kernel").value
            + registry.counter("leaf_fused_count_host").value
            + registry.counter("leaf_fused_minmax").value)


@pytest.mark.parametrize("agg", ["avg", "min", "max", "count"])
def test_fused_broadened_rate_aggs(fused_env, agg, monkeypatch):
    """avg/min/max/count by () over rate through the fused path must match
    the general path."""
    engine = _mk_engine([counter_batch(48, T, start_ms=START_MS)])
    q = f'{agg}(rate(request_total{{_ws_="demo"}}[5m])) by (_ns_)'
    _query(engine, q)                    # warm mirror
    before = _fused_all()
    got = _query(engine, q)
    assert _fused_all() > before, f"{agg} fused path did not engage"
    want = _general_query(engine, q, monkeypatch)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-4, atol=1e-3,
                                   equal_nan=True)


@pytest.mark.parametrize("fn,agg", [
    ("min_over_time", "sum"), ("max_over_time", "min"),
    ("min_over_time", "avg")])
def test_fused_minmax_over_time(fn, agg, monkeypatch):
    """min/max_over_time ride the XLA reduce_window path on any backend —
    no FILODB_TPU_FUSED_INTERPRET needed."""
    from filodb_tpu.ingest.generator import gauge_batch
    engine = _mk_engine([gauge_batch(40, T, start_ms=START_MS)])
    q = f'{agg}({fn}(heap_usage{{_ws_="demo"}}[5m])) by (_ns_)'
    _query(engine, q)                    # warm mirror
    before = registry.counter("leaf_fused_minmax").value
    got = _query(engine, q)
    assert registry.counter("leaf_fused_minmax").value > before, \
        f"{fn} reduce_window path did not engage"
    want = _general_query(engine, q, monkeypatch)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-5, atol=1e-4,
                                   equal_nan=True)


@pytest.mark.parametrize("fn,agg", [
    ("sum_over_time", "sum"), ("avg_over_time", "avg"),
    ("count_over_time", "sum"), ("min_over_time", "max")])
def test_fused_ragged_nan_working_set(fused_env, fn, agg, monkeypatch):
    """NaN-holed values on a shared grid engage the validity-weighted
    fused kinds and match the general path's NaN semantics."""
    from filodb_tpu.ingest.generator import gauge_batch
    batch = gauge_batch(24, T, start_ms=START_MS)
    vals = batch.columns["value"].copy()
    rng = np.random.default_rng(9)
    vals[rng.random(vals.shape) < 0.1] = np.nan
    vals[2 * T:3 * T] = np.nan           # one fully-absent series
    batch = RecordBatch(batch.schema, batch.part_keys, batch.part_idx,
                        batch.timestamps, {"value": vals}, batch.bucket_les)
    engine = _mk_engine([batch])
    q = f'{agg}({fn}(heap_usage{{_ws_="demo"}}[5m])) by (_ns_)'
    _query(engine, q)                    # warm mirror
    before = _fused_all()
    got = _query(engine, q)
    assert _fused_all() > before, f"ragged {fn} fused path did not engage"
    want = _general_query(engine, q, monkeypatch)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-4, atol=1e-3,
                                   equal_nan=True)


def test_fused_count_agg_pure_host(fused_env, monkeypatch):
    """count by (rate(...)) on a dense grid is host-only math."""
    engine = _mk_engine([counter_batch(30, T, start_ms=START_MS)])
    q = 'count(rate(request_total{_ws_="demo"}[5m])) by (_ns_)'
    _query(engine, q)                    # warm mirror
    before = registry.counter("leaf_fused_count_host").value
    got = _query(engine, q)
    assert registry.counter("leaf_fused_count_host").value > before
    want = _general_query(engine, q, monkeypatch)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-9,
                                   equal_nan=True)


@pytest.mark.parametrize("promql", [
    'sum(rate(request_total{_ws_="demo"}[5m])) by (_ns_)',
    'avg(increase(request_total{_ws_="demo"}[5m])) by (_ns_)',
    'max(sum_over_time(request_total{_ws_="demo"}[5m])) by (_ns_)',
    'min(min_over_time(request_total{_ws_="demo"}[5m])) by (_ns_)',
    'sum(last_over_time(request_total{_ws_="demo"}[5m])) by (_ns_)',
])
def test_host_route_matches_device_path(fused_env, monkeypatch, promql):
    """Round-5 verdict item 6: small working sets evaluate in host numpy
    (ops/hostleaf) — same results as the kernel path, decision observable
    via the leaf_host_routed counter and the explain route tag."""
    batch = counter_batch(48, T, start_ms=START_MS, resets=True)
    engine = _mk_engine([batch])
    want = _query(engine, promql)              # kernel/interpret path
    monkeypatch.setenv("FILODB_TPU_FORCE_HOST_ROUTE", "1")
    before = registry.counter("leaf_host_routed").value
    got = _query(engine, promql)
    assert registry.counter("leaf_host_routed").value > before, \
        "host route did not engage"
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-5, atol=1e-4,
                                   equal_nan=True)


def test_host_route_respects_threshold(fused_env, monkeypatch):
    """Working sets above query.host_route_max_samples stay on the
    device path (no change at 262k+ is the verdict's requirement; here
    the same property at test scale via a tiny threshold)."""
    from filodb_tpu.config import settings
    batch = counter_batch(48, T, start_ms=START_MS)
    engine = _mk_engine([batch])
    _query(engine)
    monkeypatch.setenv("FILODB_TPU_FORCE_HOST_ROUTE", "1")
    monkeypatch.setattr(settings().query, "host_route_max_samples", 10)
    before = registry.counter("leaf_host_routed").value
    _query(engine)
    assert registry.counter("leaf_host_routed").value == before


def test_fused_histogram_ragged_engages_and_matches(fused_env):
    """Round-5 verdict item 5: NaN-holed (ragged) bucket rows ride the
    fused kernel's valid-boundary machinery instead of falling to the
    general path, with per-cell presence counts — results match the
    general path including downstream histogram_quantile."""
    from filodb_tpu.ingest.generator import histogram_batch

    b = histogram_batch(12, T, start_ms=START_MS)
    hcol = b.columns["h"].copy()
    rng = np.random.default_rng(11)
    holes = rng.random(hcol.shape[0]) < 0.12     # whole scrape rows
    hcol[holes] = np.nan
    ragged = RecordBatch(b.schema, b.part_keys, b.part_idx, b.timestamps,
                         {**b.columns, "h": hcol}, b.bucket_les)
    engine = _mk_engine([ragged])
    q = ('histogram_quantile(0.9, '
         'sum(rate(http_latency{_ws_="demo"}[5m])) by (_ns_))')
    _query(engine, q)                    # warm mirror
    before = _fused_count()
    got = _query(engine, q)
    assert _fused_count() > before, "ragged hist fused path did not engage"
    import os
    os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
    want = _query(engine, q)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=5e-4, atol=1e-3,
                                   equal_nan=True)


def test_lazykeys_defers_materialization_on_fused_path():
    """RawBlock.keys must stay unmaterialized for warm fused aggregate
    queries (group ids come from the snapshot cache) and materialize
    exactly once for consumers that read per-series keys."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.execbase import LazyKeys

    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    shard.ingest(counter_batch(96, 60, start_ms=START), offset=1)
    eng = QueryEngine("prometheus", ms)
    s0 = START // 1000
    q = 'sum by (_ns_)(rate(request_total{_ws_="demo"}[5m]))'

    mats = []
    orig = LazyKeys._mat

    def counting_mat(self):
        mats.append(1)
        return orig(self)

    LazyKeys._mat = counting_mat
    try:
        r1 = eng.query_range(q, s0 + 600, 60, s0 + 600 + 1200)
        assert r1.error is None, r1.error
        warm_mats_before = len(mats)
        r2 = eng.query_range(q, s0 + 600, 60, s0 + 600 + 1200)
        assert r2.error is None
        # the WARM aggregate query must not materialize per-series keys
        assert len(mats) == warm_mats_before, \
            "warm fused query materialized per-series keys"
        # a raw selector needs them: exactly one materialization per block
        rr = eng.query_range('rate(request_total{_ns_="App-1"}[5m])',
                             s0 + 600, 60, s0 + 600 + 1200)
        assert rr.error is None
        assert len(list(rr.series())) > 0
        assert len(mats) > warm_mats_before
    finally:
        LazyKeys._mat = orig

    # sequence contract: len/bool are O(1)-safe pre-materialization
    lk = LazyKeys(shard, np.asarray([0, 1, 2]))
    assert len(lk) == 3 and bool(lk)
    assert lk._keys is None                     # len/bool didn't materialize
    assert lk[0] is not None and lk._keys is not None
