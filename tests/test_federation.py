"""Cross-cluster federation (doc/federation.md): ownership routing,
exactly-mergeable cluster partials, bit-identity vs a single-cluster
ground truth, degradation naming the dead cluster, one trace / one kill
across the boundary, and result-cache safety for federated answers.

The shared fixture is `make_federated_pair` (parallel/testcluster.py):
two FULL FiloServer clusters — east owns region="east", west owns
region="west" — federated over their doors, plus a single-store truth
engine holding every series."""
import threading
import time

import numpy as np
import pytest

from filodb_tpu.config import ConfigError, FilodbSettings
from filodb_tpu.federation.registry import ClusterDef, FederationRegistry
from filodb_tpu.core.index import Equals
from filodb_tpu.parallel.breaker import breakers
from filodb_tpu.parallel.testcluster import make_federated_pair
from filodb_tpu.query.planutils import TimeRange
from filodb_tpu.query.rangevector import PlannerParams, QueryContext
from filodb_tpu.utils.metrics import collector

S = 1_600_000_020            # first sample (seconds); data spans 1200 s


def _series_dict(res):
    assert res.error is None, res.error
    return {str(k): np.asarray(v) for k, _, v in res.series()}


def _assert_identical(got_res, want_res):
    got, want = _series_dict(got_res), _series_dict(want_res)
    assert set(got) == set(want)
    for k in want:
        assert np.array_equal(got[k], want[k], equal_nan=True), k


# ------------------------------------------------- registry unit tests


def test_cluster_def_label_ownership_is_conservative():
    cd = ClusterDef("west", host="h", port=1, match={"region": "west"})
    # provably excluded: every group's equality rejects the matcher
    assert not cd.may_own([[Equals("region", "east")]])
    assert cd.may_own([[Equals("region", "west")]])
    # unconstrained label / no region filter at all: stays in
    assert cd.may_own([[Equals("job", "api")]])
    # one group of several matching keeps the cluster in
    assert cd.may_own([[Equals("region", "east")],
                       [Equals("region", "west")]])
    # an entry with no matchers and no window owns nothing (inert)
    assert not ClusterDef("x", host="h", port=1).may_own(
        [[Equals("region", "west")]])


def test_cluster_def_time_overlap():
    cd = ClusterDef("cold", host="h", port=1,
                    time_start_ms=1000, time_end_ms=2000)
    assert cd.windowed
    eff = cd.time_overlap(TimeRange(0, 5000))
    assert (eff.start_ms, eff.end_ms) == (1000, 2000)
    assert cd.time_overlap(TimeRange(3000, 5000)) is None


def test_registry_rejects_unknown_keys_and_missing_endpoint():
    cfg = FilodbSettings().federation
    cfg.clusters = {"w": {"host": "h", "port": 1, "matchers": {}}}
    with pytest.raises(ConfigError, match="unknown keys"):
        FederationRegistry(cfg)
    cfg.clusters = {"w": {"match": {"region": "w"}}}    # no host/port
    with pytest.raises(ConfigError, match="host and port"):
        FederationRegistry(cfg)


def test_registry_owners_for_local_exclusion():
    cfg = FilodbSettings().federation
    cfg.clusters = {
        "west": {"host": "h", "port": 1, "match": {"region": "west"}},
        "east": {"local": True, "match": {"region": "east"}},
    }
    reg = FederationRegistry(cfg, local_name="east")
    tr = TimeRange(0, 1000)
    local, remotes = reg.owners_for([[Equals("region", "west")]], tr)
    assert not local and [cd.name for cd, _ in remotes] == ["west"]
    local, remotes = reg.owners_for([[Equals("region", "east")]], tr)
    assert local and remotes == []
    local, remotes = reg.owners_for([[Equals("job", "api")]], tr)
    assert local and [cd.name for cd, _ in remotes] == ["west"]


def test_overlapping_time_windows_raise():
    from filodb_tpu.federation.planner import FederationPlanner
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    cfg = FilodbSettings().federation
    cfg.clusters = {
        "a": {"host": "h", "port": 1, "time_end_ms": 2_000_000_000_000},
        "b": {"host": "h", "port": 2,
              "time_start_ms": 1_500_000_000_000},
    }
    planner = FederationPlanner(None, FederationRegistry(cfg))
    plan = query_range_to_logical_plan(
        "sum(foo)", TimeStepParams(S + 60, 60, S + 600))
    with pytest.raises(ValueError, match="overlap"):
        planner.materialize(plan, QueryContext())


def test_federated_leaf_serialization_roundtrip():
    from filodb_tpu.federation.exec import FederatedLeafExec
    from filodb_tpu.parallel import serialize
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    from filodb_tpu.query import planutils as pu
    plan = query_range_to_logical_plan(
        "sum by (_ns_) (fed_gauge)", TimeStepParams(S + 60, 60, S + 600))
    leaf = FederatedLeafExec(
        QueryContext(), dataset="prometheus", plan=plan, mode="partial",
        cluster="west", promql="sum by (_ns_) (fed_gauge)",
        traceparent="00-" + "ab" * 16 + "-" + "cd" * 8 + "-01")
    back = serialize.loads(serialize.dumps(leaf))
    assert (back.dataset, back.mode, back.cluster) == \
        ("prometheus", "partial", "west")
    assert back.traceparent == leaf.traceparent
    # the logical subtree survived byte-for-byte (grid included)
    assert pu.unparse(back.plan) == pu.unparse(plan)
    assert back.plan.start_ms == plan.start_ms
    assert back.plan.step_ms == plan.step_ms


# ------------------------------------- bit-identity vs the truth engine


@pytest.fixture(scope="module")
def pair():
    p = make_federated_pair(start=False)
    yield p
    p.stop()
    breakers.reset()


def test_pushed_aggregate_bit_identical(pair):
    q, args = "sum by (_ns_) (fed_gauge)", (S + 60, 60, S + 600)
    res = pair.engine.query_range(q, *args)
    _assert_identical(res, pair.truth.query_range(q, *args))
    # the west hop crossed as ONE [G, W] cluster partial
    assert res.stats.pushdown_pushed >= 1
    assert res.stats.wire_bytes > 0


def test_avg_pushes_exact_partials(pair):
    q, args = "avg by (_ns_) (fed_gauge)", (S + 60, 60, S + 600)
    res = pair.engine.query_range(q, *args)
    _assert_identical(res, pair.truth.query_range(q, *args))
    assert res.stats.pushdown_pushed >= 1


def test_routed_selector_whole_expression(pair):
    """{region="west"} provably excludes east: the whole expression
    routes to west and east's local stack never runs."""
    q = 'fed_gauge{region="west"}'
    args = (S + 60, 60, S + 600)
    res = pair.engine.query_range(q, *args)
    _assert_identical(res, pair.truth.query_range(q, *args))
    assert res.stats.pushdown_fallback >= 1        # series-mode hop
    assert len(_series_dict(res)) == 8             # all of west's series


def test_non_mergeable_shape_ships_series(pair):
    """A per-series expression has no mergeable partial: each cluster
    evaluates its own series and the union is exact."""
    q = "avg_over_time(fed_gauge[2m])"
    args = (S + 180, 60, S + 600)
    res = pair.engine.query_range(q, *args)
    _assert_identical(res, pair.truth.query_range(q, *args))
    assert res.stats.pushdown_fallback >= 1
    assert res.stats.pushdown_pushed == 0


def test_cross_cluster_join_bit_identical(pair):
    q = ('sum by (_ns_) (fed_gauge{region="west"}) '
         '+ sum by (_ns_) (fed_gauge{region="east"})')
    args = (S + 60, 60, S + 600)
    res = pair.engine.query_range(q, *args)
    _assert_identical(res, pair.truth.query_range(q, *args))
    assert res.stats.pushdown_pushed >= 1


def test_unsupported_shape_is_a_typed_error(pair):
    """A non-per-series, non-top-level-aggregate expression spanning
    clusters is a planning error naming the workaround, never silently
    wrong data."""
    res = pair.engine.query_range(
        "topk(2, sum by (_ns_) (fed_gauge)) / 2", S + 60, 60, S + 600)
    assert res.error is not None
    assert "federate" in res.error


def test_at_pinned_expressions_refuse_federation(pair):
    res = pair.engine.query_range(
        f"fed_gauge @ {S + 300}", S + 60, 60, S + 600)
    assert res.error is not None and "@-pinned" in res.error


# ---------------------------------------- one trace, one killable query


def test_one_trace_stitches_across_clusters(pair):
    res = pair.engine.query_range("sum by (_ns_) (fed_gauge)",
                                  S + 60, 60, S + 600)
    assert res.error is None and res.trace_id
    evs = collector.trace(res.trace_id)
    remotes = [e for e in evs if e["span"].startswith("remote_exec")]
    # west's spans came back over the wire under the SAME trace id
    assert remotes, [e["span"] for e in evs]


def test_one_query_id_spans_both_clusters(pair):
    """The federated child registers on west under the COORDINATOR's
    query id: /admin/queries shows one id, and one kill reaches the
    remote scan."""
    from filodb_tpu.query.activequeries import active_queries
    qids = []
    orig = active_queries.register
    lock = threading.Lock()

    def spy(qid, **kw):
        if kw.get("role") == "remote":
            with lock:
                qids.append(qid)
        return orig(qid, **kw)

    active_queries.register = spy
    try:
        res = pair.engine.query_range("sum by (_ns_) (fed_gauge)",
                                      S + 60, 60, S + 600)
    finally:
        active_queries.register = orig
    assert res.error is None
    assert qids and all(q == qids[0] for q in qids)


def test_kill_frame_crosses_the_door(pair):
    from filodb_tpu.parallel.transport import send_kill
    from filodb_tpu.query.activequeries import active_queries
    ent = active_queries.register("fed-kill-1", promql="[remote] leaf",
                                  origin="remote", role="remote")
    try:
        out = send_kill("127.0.0.1", pair.west.federation_door.port,
                        "fed-kill-1")
        assert out["killed"] is True and ent.token.cancelled
    finally:
        active_queries.deregister(ent, "killed")


# ----------------------------------------------- admin + health surface


def test_admin_federation_route(pair):
    pair.east.federation_registry.probe_once()
    st, payload = pair.east.api.handle("GET", "/admin/federation", {}, b"")
    assert st == 200
    rows = payload["data"]["clusters"]
    assert payload["data"]["cluster"] == "east"
    assert [r["cluster"] for r in rows] == ["west"]
    assert rows[0]["healthy"] and rows[0]["probed"]
    assert rows[0]["remoteCluster"] == "west"
    # after dispatches the breaker table carries the cluster row
    pair.engine.query_range("sum by (_ns_) (fed_gauge)",
                            S + 60, 60, S + 600)
    st, payload = pair.east.api.handle("GET", "/admin/breakers", {}, b"")
    assert st == 200
    assert any(r["peer"] == "cluster:west"
               for r in payload["data"]["breakers"])


def test_health_probe_degrades_on_dead_cluster(pair):
    reg = pair.east.federation_registry
    reg.probe_once()
    assert reg.health_probe()["status"] == "ok"
    pair.kill_west()
    try:
        reg.probe_once()
        verdict = reg.health_probe()
        assert verdict["status"] == "degraded"
        assert "west" in verdict["reason"]
    finally:
        pair.revive_west()
        reg.probe_once()
        breakers.reset()
    assert reg.health_probe()["status"] == "ok"


# -------------------------- degradation: flagged partial, breaker, recovery


def test_dead_cluster_degrades_breaker_opens_then_recovers():
    breakers.configure(failure_threshold=3, open_base_s=0.2,
                       open_max_s=0.5, jitter=0.0)
    breakers.reset()
    p = make_federated_pair(start=False)
    try:
        q, args = "sum by (_ns_) (fed_gauge)", (S + 60, 60, S + 600)
        pp = PlannerParams(allow_partial_results=True, timeout_s=10.0)
        truth = p.truth.query_range(q, *args)
        full = p.engine.query_range(q, *args, planner_params=pp)
        _assert_identical(full, truth)
        assert not full.partial
        p.kill_west()
        # never a hang, never silent short data: a flagged partial that
        # NAMES the dead cluster
        res = p.engine.query_range(q, *args, planner_params=pp)
        assert res.error is None and res.partial
        assert any("cluster:west" in w for w in res.stats.warnings), \
            res.stats.warnings
        # consecutive failures open the cluster breaker -> fail fast
        for _ in range(3):
            p.engine.query_range(q, *args, planner_params=pp)
        snap = {b["peer"]: b for b in breakers.snapshot()}
        assert snap["cluster:west"]["state"] == "open"
        t0 = time.monotonic()
        res = p.engine.query_range(q, *args, planner_params=pp)
        fast_s = time.monotonic() - t0
        assert res.partial and fast_s < 1.0, fast_s
        assert any("circuit open" in w for w in res.stats.warnings), \
            res.stats.warnings
        # half-open probe recovery: the door answers again -> full results
        p.revive_west()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            res = p.engine.query_range(q, *args, planner_params=pp)
            if res.error is None and not res.partial:
                break
            time.sleep(0.2)
        assert res.error is None and not res.partial, \
            (res.error, res.stats.warnings)
        _assert_identical(res, truth)
    finally:
        p.stop()
        breakers.configure()
        breakers.reset()


# ----------------------------------------------------- result-cache safety


def test_federated_cache_hits_tokens_and_degraded_answers():
    """Federated answers cache on the cluster set + per-cluster data
    tokens: a remote's token change invalidates, and a degraded partial
    is NEVER served from cache."""
    breakers.configure(failure_threshold=3, open_base_s=0.2,
                       open_max_s=0.5, jitter=0.0)
    breakers.reset()
    p = make_federated_pair(start=False)
    try:
        fe = p.frontend
        reg = p.east.federation_registry
        reg.probe_once()                 # tokens populated before caching
        q, args = "sum by (_ns_) (fed_gauge)", (S + 60, 60, S + 600)
        pp = PlannerParams(allow_partial_results=True, timeout_s=10.0)
        r1 = fe.query_range(q, *args, planner_params=pp)
        assert r1.error is None and not r1.partial
        r2 = fe.query_range(q, *args, planner_params=pp)
        assert r2.stats.result_cache == "hit"
        _assert_identical(r2, r1)
        # a probe transition (west dies) changes the federation token:
        # the cached full answer can no longer be served
        p.kill_west()
        reg.probe_once()
        r3 = fe.query_range(q, *args, planner_params=pp)
        assert r3.partial and r3.stats.result_cache != "hit"
        # and the partial itself is never stored: the re-poll recomputes
        r4 = fe.query_range(q, *args, planner_params=pp)
        assert r4.stats.result_cache != "hit"
        assert r4.partial
        # recovery: full answers cache again under the new token
        p.revive_west()
        reg.probe_once()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            r5 = fe.query_range(q, *args, planner_params=pp)
            if r5.error is None and not r5.partial:
                break
            time.sleep(0.2)
        assert not r5.partial, (r5.error, r5.stats.warnings)
        r6 = fe.query_range(q, *args, planner_params=pp)
        assert r6.stats.result_cache == "hit"
        _assert_identical(r6, r1)
    finally:
        p.stop()
        breakers.configure()
        breakers.reset()


def test_remote_ingest_invalidates_federated_cache():
    """West gaining NEW series changes its door's data token (rides the
    FPING reply): east's cached federated entries drop, exactly like
    local series-set changes invalidate.  (Appends strictly after the
    cached window stay a legitimate hit — the append-horizon contract —
    so the invalidation trigger here is a series-set change.)"""
    from filodb_tpu.ingest.generator import region_gauge_batch
    from filodb_tpu.gateway.router import split_batch_by_shard
    p = make_federated_pair(start=False)
    try:
        fe = p.frontend
        reg = p.east.federation_registry
        reg.probe_once()
        q, args = ('sum by (_ns_) (fed_gauge{region="west"})',
                   (S + 60, 60, S + 600))
        fe.query_range(q, *args)
        assert fe.query_range(q, *args).stats.result_cache == "hit"
        # new SERIES land on WEST only (12 > the 8 existing instances)
        batch = region_gauge_batch(12, 10, region="west", seed=9,
                                   start_ms=(S + 2000) * 1000)
        for s, sub in split_batch_by_shard(
                batch, p.west.mappers[p.dataset],
                p.west.spreads[p.dataset]).items():
            p.west.memstore.get_shard(p.dataset, s).ingest(sub)
        reg.probe_once()                 # token refresh
        assert fe.query_range(q, *args).stats.result_cache != "hit"
    finally:
        p.stop()
        breakers.reset()
