"""Ruler — recording & alerting rules engine (filodb_tpu/rules;
doc/recording_rules.md).

The contracts under test:
  * recorded series are numerically identical to hand-running the rule
    expr as an instant query at the same timestamps, and later rules in
    a group see earlier rules' output (sequential Prometheus semantics);
  * the alert state machine walks inactive -> pending (`for:`) ->
    firing -> `keep_firing_for` on a driven clock, and state survives a
    restart by replaying `ALERTS_FOR_STATE`;
  * an injected dead shard fails (and counts) the iteration WITHOUT
    recording partial output or flapping a firing alert;
  * hot reload adds/removes/modifies groups while carrying alert state
    for unchanged rules.
"""
import json
import time
import urllib.request

import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings, RulesConfig
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.frontend import QueryFrontend
from filodb_tpu.rules import (MemstoreSink, Rule, RuleGroup, Ruler,
                              RulesConfigError, WebhookNotifier,
                              load_rule_groups)
from filodb_tpu.utils.faults import faults
from filodb_tpu.utils.metrics import registry

START = 1_600_000_000_000
S_SEC = START // 1000
T = 120                                    # 20 min of 10s scrapes
DATA_END_S = S_SEC + (T - 1) * 10

EXPR = 'sum by (_ns_)(rate(request_total[5m]))'
REC = "ns:request_total:rate5m"


def _counter(name, **tags):
    return registry.counter(name, **tags).value


class _FlakySource:
    """Source wrapper whose shards can be 'killed': get_shard raises a
    ConnectionError for dead shards — the in-process analogue of a node
    death mid-evaluation."""

    def __init__(self, inner):
        self.inner = inner
        self.dead = set()

    def get_shard(self, dataset, shard):
        if shard in self.dead:
            raise ConnectionError(f"injected: shard {shard} dead")
        return self.inner.get_shard(dataset, shard)

    def shards_for(self, dataset):
        return self.inner.shards_for(dataset)


def _fixture(S=20, flaky=False):
    """(memstore, frontend, sink): S counter series on one shard with a
    frontend over them."""
    ms = TimeSeriesMemStore()
    sh = ms.setup("d", 0)
    base = counter_batch(S, 1, start_ms=START)
    row_base = np.arange(S, dtype=np.float64)[:, None]
    ts2d = np.broadcast_to(START + np.arange(T, dtype=np.int64) * 10_000,
                           (S, T))
    vals = np.arange(T, dtype=np.float64)[None, :] * 5.0 + row_base
    sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                      {"count": vals})
    source = _FlakySource(ms) if flaky else ms
    eng = QueryEngine("d", source)
    fe = QueryFrontend(eng)
    return ms, fe, MemstoreSink(ms, "d"), source


def _ruler(fe, sink, groups, **kw):
    kw.setdefault("notifier", WebhookNotifier(sleep=lambda s: None))
    kw.setdefault("config", RulesConfig())
    return Ruler(fe, sink, groups=groups, **kw)


def _vec(res):
    assert res.error is None, res.error
    out = {}
    for k, _, v in res.series():
        out[k.labels_dict.get("_ns_", "")] = float(np.asarray(v)[-1])
    return out


# ------------------------------------------------------------ config


def test_config_loads_inline_and_file(tmp_path):
    f = tmp_path / "rules.json"
    f.write_text(json.dumps({"groups": [
        {"name": "filegroup", "interval": "1m", "rules": [
            {"record": "file:metric", "expr": "sum(request_total)"},
            {"alert": "FileAlert", "expr": "sum(request_total) > 0",
             "for": "90s", "keep_firing_for": 120,
             "labels": {"severity": "page"},
             "annotations": {"summary": "hot"}},
        ]}]}))
    cfg = RulesConfig(file=str(f), groups={
        "inline": {"interval": 15, "rules": {
            "r": {"record": "inline:metric", "expr": "sum(heap_usage)"}}}})
    groups = {g.name: g for g in load_rule_groups(cfg)}
    assert set(groups) == {"filegroup", "inline"}
    fg = groups["filegroup"]
    assert fg.interval_s == 60.0 and fg.source == str(f)
    assert fg.rules[0].kind == "recording"
    al = fg.rules[1]
    assert (al.kind, al.for_s, al.keep_firing_for_s) == ("alerting",
                                                         90.0, 120.0)
    assert al.labels_dict == {"severity": "page"}
    assert groups["inline"].interval_s == 15.0


@pytest.mark.parametrize("raw", [
    {"record": "bad name", "expr": "sum(x)"},        # bad metric name
    {"record": "ok", "expr": "sum(("},               # bad PromQL
    {"record": "ok", "expr": "sum(x)", "for": "1m"},  # for on recording
    {"alert": "A"},                                  # missing expr
    {"record": "ok", "alert": "A", "expr": "x"},     # both kinds
    {"record": "ok", "expr": "x", "bogus": 1},       # unknown key
])
def test_config_rejects_bad_rules(raw):
    cfg = RulesConfig(groups={"g": {"rules": {"r": raw}}})
    with pytest.raises(RulesConfigError):
        load_rule_groups(cfg)


def test_config_rejects_duplicate_groups(tmp_path):
    f = tmp_path / "rules.json"
    f.write_text(json.dumps({"groups": [
        {"name": "g", "rules": [{"record": "a:b", "expr": "sum(x)"}]}]}))
    cfg = RulesConfig(file=str(f), groups={
        "g": {"rules": {"r": {"record": "a:b", "expr": "sum(x)"}}}})
    with pytest.raises(RulesConfigError, match="defined twice"):
        load_rule_groups(cfg)


def test_settings_overlay_parses_rules_block():
    s = FilodbSettings()
    s.overlay({"rules": {"enabled": True, "default_interval_s": 15,
                         "groups": {"g": {"rules": {
                             "r": {"record": "a:b", "expr": "sum(x)"}}}}}})
    assert s.rules.enabled is True
    groups = load_rule_groups(s.rules)
    assert groups[0].interval_s == 15.0


# --------------------------------------------------------- recording


def test_recorded_identical_to_adhoc_instant_queries():
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (Rule(REC, EXPR, "recording"),))
    ruler = _ruler(fe, sink, [g])
    ticks = [DATA_END_S - 60, DATA_END_S - 30, DATA_END_S]
    for ts in ticks:
        assert ruler.evaluate_group("g", ts=ts)
    for ts in ticks:
        hand = _vec(fe.query_instant(EXPR, ts))
        rec = _vec(fe.query_instant(REC, ts))
        assert set(hand) == set(rec) and len(hand) > 0
        for ns in hand:
            # bit-identical: the recorded sample IS the evaluated value
            assert rec[ns] == hand[ns], (ts, ns)


def test_later_rules_see_earlier_rules_output():
    """Prometheus sequential-evaluation semantics: rule 2 aggregates
    rule 1's freshly-recorded series AT THE SAME evaluation ts."""
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (
        Rule(REC, EXPR, "recording"),
        Rule("total:rate5m", f"sum({REC})", "recording"),
    ))
    ruler = _ruler(fe, sink, [g])
    ts = DATA_END_S
    assert ruler.evaluate_group("g", ts=ts)
    first = _vec(fe.query_instant(REC, ts))
    second = fe.query_instant("total:rate5m", ts)
    vals = [float(np.asarray(v)[-1]) for _, _, v in second.series()]
    assert len(vals) == 1
    np.testing.assert_allclose(vals[0], sum(first.values()), rtol=1e-6)


def test_recording_labels_override_and_rename():
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (
        Rule(REC, EXPR, "recording", labels=(("tier", "gold"),)),))
    ruler = _ruler(fe, sink, [g])
    assert ruler.evaluate_group("g", ts=DATA_END_S)
    res = fe.query_instant(REC + '{tier="gold"}', DATA_END_S)
    assert res.error is None and res.num_series > 0
    for k, _, _v in res.series():
        lab = k.labels_dict
        assert lab["_metric_"] == REC and lab["tier"] == "gold"


# ------------------------------------------------------ alert machine


def test_alert_transitions_pending_firing_keep_firing():
    _, fe, sink, _ = _fixture()
    alert = Rule("HighRate", "sum(rate(request_total[5m])) > 0",
                 "alerting", labels=(("severity", "page"),),
                 annotations=(("summary", "traffic exists"),),
                 for_s=60.0, keep_firing_for_s=120.0)
    g = RuleGroup("g", 30.0, (alert,))
    # resend disabled: this test asserts transitions-only delivery
    ruler = _ruler(fe, sink, [g],
                   config=RulesConfig(notify_resend_delay_s=0.0))
    t1 = DATA_END_S - 120
    # inactive -> pending
    assert ruler.evaluate_group("g", ts=t1)
    alerts = ruler.alerts_payload()["alerts"]
    assert [a["state"] for a in alerts] == ["pending"]
    assert alerts[0]["labels"] == {"alertname": "HighRate",
                                   "severity": "page"}
    assert ruler.notifier.snapshot() == []
    # still pending inside `for:`
    assert ruler.evaluate_group("g", ts=t1 + 30)
    assert ruler.alerts_payload()["alerts"][0]["state"] == "pending"
    # pending -> firing once `for:` elapses; ONE notification
    assert ruler.evaluate_group("g", ts=t1 + 60)
    fired = ruler.alerts_payload()["alerts"]
    assert fired[0]["state"] == "firing"
    sent = ruler.notifier.snapshot()
    assert len(sent) == 1
    assert sent[0]["alerts"][0]["labels"]["alertname"] == "HighRate"
    assert sent[0]["alerts"][0]["annotations"] == {
        "summary": "traffic exists"}
    # ALERTS/ALERTS_FOR_STATE synthetic series are queryable
    res = fe.query_instant('ALERTS{alertstate="firing"}', t1 + 60)
    assert res.error is None and res.num_series == 1
    res = fe.query_instant('ALERTS_FOR_STATE{alertname="HighRate"}',
                           t1 + 60)
    assert [float(np.asarray(v)[-1])
            for _, _, v in res.series()] == [float(t1)]
    # expr goes absent (past the data + rate window): keep_firing_for
    # holds the firing state...
    t_gone = DATA_END_S + 400
    assert ruler.evaluate_group("g", ts=t_gone)
    assert ruler.alerts_payload()["alerts"][0]["state"] == "firing"
    # ...until it elapses -> inactive
    assert ruler.evaluate_group("g", ts=t_gone + 121)
    assert ruler.alerts_payload()["alerts"] == []
    assert len(ruler.notifier.snapshot()) == 1    # no re-notify spam


def test_pending_alert_clears_without_firing():
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (
        Rule("A", "sum(rate(request_total[5m])) > 0", "alerting",
             for_s=600.0),))
    ruler = _ruler(fe, sink, [g])
    assert ruler.evaluate_group("g", ts=DATA_END_S)
    assert ruler.alerts_payload()["alerts"][0]["state"] == "pending"
    assert ruler.evaluate_group("g", ts=DATA_END_S + 400)  # expr absent
    assert ruler.alerts_payload()["alerts"] == []
    assert ruler.notifier.snapshot() == []


def test_alert_state_restored_after_restart():
    """`for:` clocks survive restart: a new Ruler over the same store
    replays ALERTS_FOR_STATE and fires WITHOUT resetting the pending
    window."""
    _, fe, sink, _ = _fixture()
    mk = lambda: RuleGroup("g", 30.0, (
        Rule("Slow", "sum(rate(request_total[5m])) > 0", "alerting",
             for_s=240.0),))
    t1 = DATA_END_S - 240
    r1 = _ruler(fe, sink, [mk()])
    assert r1.evaluate_group("g", ts=t1)
    assert r1.alerts_payload()["alerts"][0]["state"] == "pending"
    # "restart": fresh Ruler, no in-memory state
    r2 = _ruler(fe, sink, [mk()])
    assert r2.evaluate_group("g", ts=t1 + 240)
    alerts = r2.alerts_payload()["alerts"]
    assert [a["state"] for a in alerts] == ["firing"]
    # activeAt is the ORIGINAL activation, not the restart time
    from filodb_tpu.rules.ruler import _iso
    assert alerts[0]["activeAt"] == _iso(float(t1))
    assert len(r2.notifier.snapshot()) == 1


# ------------------------------------------------------ failure domain


def test_dead_shard_fails_iteration_without_partials_or_flapping():
    ms, fe, sink, source = _fixture(flaky=True)
    g = RuleGroup("g", 30.0, (
        Rule(REC, EXPR, "recording"),
        Rule("Any", "sum(rate(request_total[5m])) > 0", "alerting"),))
    ruler = _ruler(fe, sink, [g])
    t1 = DATA_END_S - 60
    assert ruler.evaluate_group("g", ts=t1)
    assert ruler.alerts_payload()["alerts"][0]["state"] == "firing"
    sh = ms.get_shard("d", 0)
    rows_before = sh.stats.rows_ingested
    fails0 = _counter("rule_evaluation_failures", group="g")
    # kill the shard mid-evaluation-cycle
    source.dead.add(0)
    assert ruler.evaluate_group("g", ts=t1 + 30) is False
    assert _counter("rule_evaluation_failures", group="g") - fails0 == 2
    # nothing recorded from the failed iteration (no partial write-back)
    assert sh.stats.rows_ingested == rows_before
    # the firing alert did NOT flap: state + activeAt held, no resolve,
    # no duplicate notification
    alerts = ruler.alerts_payload()["alerts"]
    assert [a["state"] for a in alerts] == ["firing"]
    assert len(ruler.notifier.snapshot()) == 1
    # per-rule health surfaces the error
    payload = ruler.rules_payload()["groups"][0]
    assert all(r["health"] == "err" and r["lastError"]
               for r in payload["rules"])
    # shard comes back: evaluation resumes cleanly
    source.dead.discard(0)
    assert ruler.evaluate_group("g", ts=t1 + 60)
    assert sh.stats.rows_ingested > rows_before
    assert all(r["health"] == "ok"
               for r in ruler.rules_payload()["groups"][0]["rules"])


def test_write_back_fault_fails_iteration():
    """ingest.batch chaos (utils/faults): the write-back raising fails
    the iteration BEFORE any sample lands."""
    ms, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (Rule(REC, EXPR, "recording"),))
    ruler = _ruler(fe, sink, [g])
    sh = ms.get_shard("d", 0)
    rows_before = sh.stats.rows_ingested
    with faults.plan("ingest.batch", "error", first_k=1):
        assert ruler.evaluate_group("g", ts=DATA_END_S) is False
    assert sh.stats.rows_ingested == rows_before


def test_notifier_retry_backoff_and_drop():
    sleeps = []
    n = WebhookNotifier(retries=3, backoff_s=0.5, sleep=sleeps.append)
    with faults.plan("ruler.notify", "error", first_k=2):
        assert n.notify([{"labels": {"alertname": "A"}}]) is True
    assert sleeps == [0.5, 1.0]            # exponential backoff
    assert len(n.snapshot()) == 1
    dropped0 = _counter("rule_notifications_dropped")
    with faults.plan("ruler.notify", "error", first_k=99):
        assert n.notify([{"labels": {"alertname": "A"}}]) is False
    assert _counter("rule_notifications_dropped") - dropped0 == 1


# --------------------------------------------------------- hot reload


def test_hot_reload_add_remove_modify_preserves_state():
    _, fe, sink, _ = _fixture()
    alert = Rule("Any", "sum(rate(request_total[5m])) > 0", "alerting",
                 for_s=0.0)
    ga = RuleGroup("a", 30.0, (alert, Rule(REC, EXPR, "recording")))
    gb = RuleGroup("b", 30.0, (Rule("b:m", "sum(heap_usage)",
                                    "recording"),))
    ruler = _ruler(fe, sink, [ga, gb])
    t1 = DATA_END_S
    assert ruler.evaluate_group("a", ts=t1)
    active_at = ruler.alerts_payload()["alerts"][0]["activeAt"]
    # modify a: new recording rule rides along, alert rule unchanged;
    # drop b; add c
    ga2 = RuleGroup("a", 30.0, (alert, Rule(REC, EXPR, "recording"),
                                Rule("extra:m", "sum(request_total)",
                                     "recording")))
    gc = RuleGroup("c", 60.0, (Rule("c:m", "sum(request_total)",
                                    "recording"),))
    summary = ruler.reload([ga2, gc])
    assert summary == {"groups": 2, "added": ["c"], "removed": ["b"],
                       "changed": ["a"]}
    assert ruler.group_names() == ["a", "c"]
    # the unchanged alert rule kept its instance (activeAt preserved)
    alerts = ruler.alerts_payload()["alerts"]
    assert [a["activeAt"] for a in alerts] == [active_at]
    assert ruler.evaluate_group("c", ts=t1 + 30)
    with pytest.raises(KeyError):
        ruler.evaluate_group("b", ts=t1 + 30)
    # invalid reload leaves running state untouched
    with pytest.raises(RulesConfigError):
        ruler.reload([gc, gc])
    assert ruler.group_names() == ["a", "c"]


def test_reload_rereads_config_source():
    """An argless reload() pulls a FRESH config through config_source
    (standalone wires one that re-reads the conf file from disk), so
    edits to the inline rules.groups block land without a restart."""
    _, fe, sink, _ = _fixture()
    cfgs = [RulesConfig(groups={"g1": {"interval": 30, "rules": {
                "r": {"record": REC, "expr": EXPR}}}}),
            RulesConfig(groups={"g2": {"interval": 60, "rules": {
                "r": {"record": "other:m", "expr": "sum(heap_usage)"}}}})]
    ruler = _ruler(fe, sink, None, config_source=lambda: cfgs.pop(0))
    summary = ruler.reload()
    assert summary["added"] == ["g1"]
    summary = ruler.reload()
    assert summary == {"groups": 1, "added": ["g2"], "removed": ["g1"],
                       "changed": []}
    # a config_source that blows up (bad conf file) is a RulesConfigError
    # (-> HTTP 400) and the running groups stay live
    ruler.config_source = lambda: (_ for _ in ()).throw(OSError("gone"))
    with pytest.raises(RulesConfigError):
        ruler.reload()
    assert ruler.group_names() == ["g2"]


# ---------------------------------------------------------- scheduler


def test_scheduler_evaluates_on_interval():
    import time as _time
    _, fe, sink, _ = _fixture(S=4)
    g = RuleGroup("sched", 0.2, (Rule(REC, EXPR, "recording"),))
    # clock pinned inside the data window so the expr yields output
    offset = DATA_END_S - _time.time()
    ruler = _ruler(fe, sink, [g], clock=lambda: _time.time() + offset)
    ruler.start()
    try:
        deadline = _time.time() + 10.0
        while _time.time() < deadline:
            gs = ruler.rules_payload()["groups"][0]
            if gs["rules"][0]["health"] == "ok":
                break
            _time.sleep(0.05)
        assert ruler.rules_payload()["groups"][0]["rules"][0][
            "health"] == "ok", "scheduler never evaluated the group"
    finally:
        ruler.stop()


def test_stagger_is_deterministic_per_group():
    from filodb_tpu.utils.hashing import xxhash32
    s1 = (xxhash32(b"group-one") % 30_000) / 1000.0
    s2 = (xxhash32(b"group-one") % 30_000) / 1000.0
    s3 = (xxhash32(b"group-two") % 30_000) / 1000.0
    assert s1 == s2
    assert 0.0 <= s1 < 30.0 and s1 != s3


# ----------------------------------------------------------- HTTP API


@pytest.fixture()
def server():
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    cfg = FilodbSettings()
    cfg.rules.enabled = True
    cfg.rules.groups = {
        "agg": {"interval": "30s", "rules": {
            "r1": {"record": REC, "expr": EXPR},
            "a1": {"alert": "AnyTraffic",
                   "expr": "sum(rate(request_total[5m])) > 0",
                   "labels": {"severity": "page"}},
        }}}
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     config=cfg, http_port=0)
    sh = srv.memstore.get_shard("prometheus", 0)
    sh.ingest(counter_batch(6, T, start_ms=START))
    srv.start(background_flush=False)
    # retire the live group runners: these tests drive evaluate_group
    # at pinned historical timestamps, and a wall-clock tick landing
    # mid-test would evaluate at NOW (no data there), resolve the alert,
    # and flake the payload assertions (~once per 20 runs)
    srv.ruler.stop()
    yield srv
    srv.shutdown()


def _get(srv, path, method="GET", **params):
    import urllib.parse
    url = f"http://127.0.0.1:{srv.http.port}{path}"
    if params:
        url += "?" + urllib.parse.urlencode(params)
    req = urllib.request.Request(
        url, data=(b"" if method == "POST" else None), method=method)
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_http_rules_and_alerts_payload_shape(server):
    server.ruler.evaluate_group("agg", ts=DATA_END_S)
    st, payload = _get(server, "/api/v1/rules")
    assert st == 200 and payload["status"] == "success"
    groups = payload["data"]["groups"]
    assert len(groups) == 1 and groups[0]["name"] == "agg"
    by_type = {r["type"]: r for r in groups[0]["rules"]}
    rec = by_type["recording"]
    assert rec["name"] == REC and rec["health"] == "ok"
    assert rec["lastEvaluation"].endswith("Z")
    assert rec["evaluationTime"] >= 0
    al = by_type["alerting"]
    assert al["state"] == "firing" and al["duration"] == 0.0
    assert al["alerts"][0]["labels"]["severity"] == "page"
    # ?type= filter (the Prometheus param)
    st, only_rec = _get(server, "/api/v1/rules", type="record")
    kinds = {r["type"] for g in only_rec["data"]["groups"]
             for r in g["rules"]}
    assert kinds == {"recording"}
    st, alerts = _get(server, "/api/v1/alerts")
    assert st == 200
    assert [a["state"] for a in alerts["data"]["alerts"]] == ["firing"]


def test_http_rules_reload(server):
    st, payload = _get(server, "/admin/rules/reload", method="POST")
    assert st == 200 and payload["data"]["groups"] == 1
    # recorded series from before the reload still serve
    server.ruler.evaluate_group("agg", ts=DATA_END_S)
    st, q = _get(server, "/api/v1/query", query=REC, time=DATA_END_S)
    assert st == 200 and len(q["data"]["result"]) > 0


def test_http_status_endpoints(server):
    from filodb_tpu import __version__
    st, b = _get(server, "/api/v1/status/buildinfo")
    assert st == 200 and b["data"]["version"] == __version__
    st, r = _get(server, "/api/v1/status/runtimeinfo")
    assert st == 200
    data = r["data"]
    assert data["startTime"].endswith("Z")
    assert data["timeSeriesCount"] >= 6
    assert data["reloadConfigSuccess"] is True
    assert data["storageRetention"].endswith("s")


def test_http_instant_query_goes_through_frontend(server):
    """Satellite: /api/v1/query rides the QueryFrontend — tenant usage
    accounting (and therefore admission/limits) now sees instant
    queries, which the old direct-engine call bypassed."""
    from filodb_tpu.utils.usage import usage
    usage.clear()
    st, _p = _get(server, "/api/v1/query",
                  query='request_total{_ws_="demo"}', time=DATA_END_S)
    assert st == 200
    st, u = _get(server, "/api/v1/usage")
    tenants = {(t["ws"], t["ns"]) for t in u["data"]}
    assert ("demo", "") in tenants
    # and the ruler's evaluations bill to the `_rules_` bucket
    server.ruler.evaluate_group("agg", ts=DATA_END_S)
    st, u = _get(server, "/api/v1/usage")
    tenants = {(t["ws"], t["ns"]) for t in u["data"]}
    assert ("_rules_", "agg") in tenants


# ---------------------------------------------------- review-pass fixes


def test_fractional_tick_records_at_eval_timestamp():
    """Production ticks carry a sub-second stagger: evaluation and
    write-back must land on the SAME whole-second timestamp or a
    second-order rule in the group queries 'before' the sample its
    predecessor just recorded."""
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (
        Rule(REC, EXPR, "recording"),
        Rule("total:sum", f"sum({REC})", "recording"),))
    ruler = _ruler(fe, sink, [g])
    assert ruler.evaluate_group("g", ts=DATA_END_S + 0.345)
    # the second-order rule saw the first rule's output in THIS iteration
    res = fe.query_instant("total:sum", DATA_END_S)
    assert res.error is None and res.num_series == 1
    ts_ms = [int(np.asarray(w)[-1]) for _, w, _ in res.series()]
    assert ts_ms == [DATA_END_S * 1000]


def test_alert_state_holds_when_synthetic_write_back_fails():
    """A failed ALERTS/ALERTS_FOR_STATE write fails the iteration BEFORE
    the new alert map publishes: no transition the store never saw."""
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (
        Rule("Any", "sum(rate(request_total[5m])) > 0", "alerting",
             for_s=0.0),))
    ruler = _ruler(fe, sink, [g])
    with faults.plan("ingest.batch", "error", first_k=1):
        assert ruler.evaluate_group("g", ts=DATA_END_S) is False
    assert ruler.alerts_payload()["alerts"] == []   # no phantom firing
    # clean retry transitions normally
    assert ruler.evaluate_group("g", ts=DATA_END_S + 30)
    assert ruler.alerts_payload()["alerts"][0]["state"] == "firing"


def test_notifier_batch_is_webhook_shaped():
    """Delivered batches use the Alertmanager v4 *webhook* alert shape
    (status/startsAt/endsAt), not the /api/v1/alerts API shape."""
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (
        Rule("Any", "sum(rate(request_total[5m])) > 0", "alerting",
             for_s=0.0),))
    ruler = _ruler(fe, sink, [g])
    assert ruler.evaluate_group("g", ts=DATA_END_S)
    (sent,) = ruler.notifier.snapshot()
    assert sent["version"] == "4" and sent["status"] == "firing"
    (alert,) = sent["alerts"]
    assert alert["status"] == "firing"
    assert alert["startsAt"].endswith("Z") and alert["endsAt"] == ""
    assert alert["labels"]["alertname"] == "Any"
    assert "state" not in alert and "activeAt" not in alert


def test_argless_reload_refused_without_config_source():
    """Ruler(groups=[...]) with a bare RulesConfig: an argless reload()
    must refuse (RulesConfigError -> HTTP 400) instead of loading an
    empty config and silently retiring every running group."""
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 30.0, (Rule(REC, EXPR, "recording"),))
    ruler = _ruler(fe, sink, [g])
    with pytest.raises(RulesConfigError, match="no reloadable"):
        ruler.reload()
    assert ruler.group_names() == ["g"]             # untouched
    assert ruler.evaluate_group("g", ts=DATA_END_S)


def test_group_deadline_not_capped_by_default_timeout():
    """A group interval above query.default_timeout_s still gets its
    full slot: the ruler stamps an absolute deadline (uncapped by
    compute_deadline's min() rule) instead of passing timeout_s."""
    from filodb_tpu.query.rangevector import compute_deadline
    _, fe, sink, _ = _fixture()
    g = RuleGroup("g", 300.0, (Rule(REC, EXPR, "recording"),))
    ruler = _ruler(fe, sink, [g])
    t0 = time.time()
    pp = ruler._planner_params(g)
    assert pp.deadline_unix_s >= t0 + 299.0
    # compute_deadline honors the stamp uncapped (default cap is 120 s)
    assert compute_deadline(pp, 120.0) == pp.deadline_unix_s
    assert ruler.evaluate_group("g", ts=DATA_END_S)


def test_resolved_alert_not_resurrected_by_restart():
    """A resolved episode writes NaN staleness markers: a restart inside
    the stale-lookback window must NOT replay the old activeAt (which
    would skip the `for:` hold and fire immediately)."""
    ms, fe, sink, _ = _fixture()
    mk = lambda: RuleGroup("g", 30.0, (
        Rule("Any", "sum(rate(request_total[5m])) > 0", "alerting",
             for_s=120.0),))
    r1 = _ruler(fe, sink, [mk()])
    t1 = DATA_END_S - 120
    assert r1.evaluate_group("g", ts=t1)                  # pending
    assert r1.evaluate_group("g", ts=DATA_END_S)          # firing
    assert r1.evaluate_group("g", ts=DATA_END_S + 250)    # still firing
    assert [a["state"] for a in r1.alerts_payload()["alerts"]] \
        == ["firing"]
    # expr absent past data + rate window: resolves, markers written
    assert r1.evaluate_group("g", ts=DATA_END_S + 310)
    assert r1.alerts_payload()["alerts"] == []
    # traffic returns in a SECOND data window
    sh = ms.get_shard("d", 0)
    base = counter_batch(20, 1, start_ms=START)
    row_base = np.arange(20, dtype=np.float64)[:, None]
    ts2 = np.broadcast_to(
        (DATA_END_S + 320) * 1000
        + np.arange(30, dtype=np.int64) * 10_000, (20, 30))
    vals2 = np.arange(30, dtype=np.float64)[None, :] * 7.0 + row_base
    sh.ingest_columns("prom-counter", base.part_keys, ts2,
                      {"count": vals2})
    # restart INSIDE the lookback of the resolved episode's last real
    # ALERTS_FOR_STATE sample (DATA_END+250): the NaN marker at +310
    # must hide it, so this is a FRESH pending episode, not instant fire
    r2 = _ruler(fe, sink, [mk()])
    t_restart = DATA_END_S + 450
    assert r2.evaluate_group("g", ts=t_restart)
    alerts = r2.alerts_payload()["alerts"]
    assert [a["state"] for a in alerts] == ["pending"]
    from filodb_tpu.rules.ruler import _iso
    assert alerts[0]["activeAt"] == _iso(float(t_restart))


def test_reload_rebuilds_owned_notifier():
    """notify_* edits land on /admin/rules/reload when the ruler built
    its own notifier from config; injected notifiers are untouched."""
    _, fe, sink, _ = _fixture()
    grp = {"g": {"interval": 30, "rules": {
        "r": {"record": REC, "expr": EXPR}}}}
    owned = Ruler(fe, sink, config=RulesConfig(groups=grp))
    assert owned.notifier.url == ""
    owned.config_source = lambda: RulesConfig(
        groups=grp, notify_url="http://am.example/webhook",
        notify_retries=1)
    owned.reload()
    assert owned.notifier.url == "http://am.example/webhook"
    assert owned.notifier.retries == 1
    injected = WebhookNotifier(sleep=lambda s: None)
    ruler = Ruler(fe, sink, config=RulesConfig(groups=grp),
                  notifier=injected)
    ruler.config_source = lambda: RulesConfig(
        groups=grp, notify_url="http://other/")
    ruler.reload()
    assert ruler.notifier is injected


def test_rules_tenant_exempt_from_scan_limits():
    """query.tenant_samples_*_limit must not starve the ruler: the
    `_rules_` workspace is accounted but exempt from the admit gate
    (aggregation rules legitimately scan the whole store every tick)."""
    from filodb_tpu.utils.usage import usage
    usage.clear()
    usage.record_query("_rules_", "g", 0.1, 10_000, 0)
    usage.record_query("heavy", "", 0.1, 10_000, 0)
    assert usage.admit("_rules_", "g", 10, 100) is None
    err = usage.admit("heavy", "", 10, 100)
    assert err is not None and "tenant_limit_exceeded" in err
