"""Incremental device-mirror updates: append-only ingest must produce a
mirror numerically identical to a from-scratch upload (transfer O(new
samples)); anything that rearranges cells must fall back to a full
refresh (ref: BlockManager working-set semantics; devicecache.py)."""
import numpy as np
import pytest

from filodb_tpu.core.devicecache import DeviceMirror
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.ingest.generator import counter_batch, histogram_batch
from filodb_tpu.utils.metrics import registry

START = 1_600_000_000_000


def _slices(batch, bounds):
    for lo_i, hi_i in bounds:
        lo = START + lo_i * 10_000
        hi = START + hi_i * 10_000
        k = (batch.timestamps >= lo) & (batch.timestamps < hi)
        yield RecordBatch(batch.schema, batch.part_keys, batch.part_idx[k],
                          batch.timestamps[k],
                          {kk: v[k] for kk, v in batch.columns.items()},
                          batch.bucket_les)


def _mirror_state(mirror, store):
    snap = mirror._snap
    out = {"ts": np.asarray(snap.ts_off)}
    for n, a in snap.cols.items():
        out[f"col_{n}"] = np.asarray(a)
        # reconstruct ABSOLUTE values: rebased + vbase (bases may differ
        # between incremental and full paths for fresh rows; absolutes
        # must not)
        vb = np.asarray(snap.vbases[n])
        out[f"abs_{n}"] = out[f"col_{n}"] + (
            vb[:, None, :] if out[f"col_{n}"].ndim == 3 else vb[:, None])
    return out


def _assert_equivalent(store, mirror):
    """Mirror state after incremental updates == a fresh full upload."""
    fresh = DeviceMirror()
    assert fresh._refresh(store)
    a, b = _mirror_state(mirror, store), _mirror_state(fresh, store)
    np.testing.assert_array_equal(a["ts"], b["ts"])
    for k in b:
        if k.startswith("abs_"):
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-6,
                                       equal_nan=True)


def _incr_count():
    return registry.counter("device_mirror_incremental").value


def test_append_only_counter_updates_incrementally():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    # resets=True exercises tail reset-correction continuation
    full = counter_batch(30, 200, start_ms=START, resets=True)
    slices = list(_slices(full, [(0, 50), (50, 90), (90, 140), (140, 200)]))
    sh.ingest(slices[0], offset=0)
    store = sh.stores["prom-counter"]
    mirror = DeviceMirror()
    assert mirror.ensure_fresh(store)
    before = _incr_count()
    for i, sl in enumerate(slices[1:], 1):
        sh.ingest(sl, offset=i)
        assert mirror.ensure_fresh(store)
        _assert_equivalent(store, mirror)
    assert _incr_count() - before >= 3, "appends did not take the fast path"


def test_new_series_and_time_growth():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    base = counter_batch(10, 60, start_ms=START)
    sh.ingest(base, offset=0)
    store = sh.stores["prom-counter"]
    mirror = DeviceMirror()
    assert mirror.ensure_fresh(store)
    # NEW series appear later (S growth) while old ones extend (T growth);
    # sized so new cells stay under the incremental threshold
    from filodb_tpu.core.partkey import PartKey
    ext = counter_batch(10, 90, start_ms=START)
    k = ext.timestamps >= START + 60 * 10_000
    sh.ingest(RecordBatch(ext.schema, ext.part_keys, ext.part_idx[k],
                          ext.timestamps[k],
                          {kk: v[k] for kk, v in ext.columns.items()},
                          ext.bucket_les), offset=1)
    more = counter_batch(3, 90, start_ms=START, seed=9)
    keys = [PartKey.make(pk.metric, {**dict(pk.tags), "inst": f"n{i}"})
            for i, pk in enumerate(more.part_keys)]
    more = RecordBatch(more.schema, keys, more.part_idx, more.timestamps,
                       more.columns, more.bucket_les)
    sh.ingest(more, offset=2)
    before = _incr_count()
    assert mirror.ensure_fresh(store)
    assert _incr_count() == before + 1
    _assert_equivalent(store, mirror)

    # a growth burst past the threshold correctly chooses the full upload
    burst = counter_batch(40, 400, start_ms=START, seed=11)
    keys2 = [PartKey.make(pk.metric, {**dict(pk.tags), "inst": f"b{i}"})
             for i, pk in enumerate(burst.part_keys)]
    sh.ingest(RecordBatch(burst.schema, keys2, burst.part_idx,
                          burst.timestamps, burst.columns,
                          burst.bucket_les), offset=3)
    before = _incr_count()
    assert mirror.ensure_fresh(store)
    assert _incr_count() == before, "burst should take the full path"
    _assert_equivalent(store, mirror)


def test_histogram_incremental():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    full = histogram_batch(8, 120, start_ms=START)
    mirror = DeviceMirror()
    before = _incr_count()
    # slice sizes comfortably below the 50% threshold so the [R, L, B]
    # seeded-correction path is guaranteed exercised, not silently skipped
    for i, sl in enumerate(_slices(full, [(0, 60), (60, 90), (90, 120)])):
        sh.ingest(sl, offset=i)
        store = sh.stores["prom-histogram"]
        assert mirror.ensure_fresh(store)
        _assert_equivalent(store, mirror)
    assert _incr_count() - before >= 2, \
        "histogram appends did not take the incremental path"


def test_all_nan_row_gets_real_vbase_on_first_finite_append():
    """A row whose first upload had no finite values (vbase 0) must get a
    REAL base from its first finite append — large counters would
    otherwise land on device un-rebased and lose their f32 deltas."""
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    base = counter_batch(4, 40, start_ms=START)
    nan_cols = {k: np.full_like(v, np.nan) for k, v in base.columns.items()}
    sh.ingest(RecordBatch(base.schema, base.part_keys, base.part_idx,
                          base.timestamps, nan_cols, base.bucket_les),
              offset=0)
    store = sh.stores["prom-counter"]
    mirror = DeviceMirror()
    assert mirror.ensure_fresh(store)
    # now append HUGE counter values where f32 absolute storage loses +1s
    big = 2.0 ** 31
    n = 20
    ts = np.tile(START + (40 + np.arange(n, dtype=np.int64)) * 10_000, 4)
    idx = np.repeat(np.arange(4, dtype=np.int32), n)
    vals = big + np.arange(n, dtype=np.float64)[None, :] + \
        np.arange(4)[:, None] * 1000.0
    sh.ingest(RecordBatch(base.schema, base.part_keys, idx, ts,
                          {"count": vals.ravel()}), offset=1)
    before = _incr_count()
    assert mirror.ensure_fresh(store)
    assert _incr_count() == before + 1
    snap = mirror._snap
    rb = np.asarray(snap.cols["count"])
    finite = rb[np.isfinite(rb)]
    # rebased device values must be SMALL (deltas preserved in f32)
    assert np.abs(finite).max() < 1e5, np.abs(finite).max()


def test_rearranging_ops_fall_back_to_full_refresh():
    cs_ms = TimeSeriesMemStore()
    sh = cs_ms.setup("prometheus", 0)
    sh.ingest(counter_batch(10, 120, start_ms=START), offset=0)
    store = sh.stores["prom-counter"]
    mirror = DeviceMirror()
    assert mirror.ensure_fresh(store)
    sv = store.shift_version
    # eviction shifts cells -> shift_version bumps -> incremental refused
    sh.flush_all_groups()
    store.evict_oldest(30)
    assert store.shift_version > sv
    before = _incr_count()
    assert mirror.ensure_fresh(store)
    assert _incr_count() == before, "shifted store must NOT go incremental"
    _assert_equivalent(store, mirror)


def test_incremental_correctness_through_query_path(monkeypatch):
    """End-to-end: rates served from an incrementally-updated mirror match
    a mirror-disabled engine exactly."""
    from filodb_tpu.query.engine import QueryEngine
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    full = counter_batch(20, 240, start_ms=START, resets=True)
    eng = QueryEngine("prometheus", ms)
    s = START // 1000

    def q(e):
        r = e.query_range('sum by (_ns_)(rate(request_total[5m]))',
                          s + 600, 60, s + 2390)
        assert r.error is None, r.error
        return {str(k): np.asarray(v) for k, _, v in r.series()}

    for i, sl in enumerate(_slices(full, [(0, 80), (80, 160), (160, 240)])):
        sh.ingest(sl, offset=i)
        got = q(eng)
    # truth: same data, mirror disabled
    ms2 = TimeSeriesMemStore()
    sh2 = ms2.setup("prometheus", 0)
    # config.store is the process-wide settings() singleton: restore the
    # flag after the test or every later store silently loses its mirror
    # (this leak hid the fused path from any test running after this one)
    monkeypatch.setattr(sh2.config.store, "device_mirror_enabled", False)
    sh2.ingest(counter_batch(20, 240, start_ms=START, resets=True), offset=0)
    want = q(QueryEngine("prometheus", ms2))
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5,
                                   equal_nan=True)


def test_series_growth_with_zero_new_samples_pads_without_error():
    """A new row registered with no surviving samples (s grows, no new
    cells) must take the cheap pad-only path, not the error fallback."""
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(10, 60, start_ms=START), offset=0)
    store = sh.stores["prom-counter"]
    mirror = DeviceMirror()
    assert mirror.ensure_fresh(store)
    # register a row directly with zero samples (what a fully-dropped
    # out-of-order batch leaves behind), bumping the generation
    with store.mutation():
        store.new_row()
    before_err = registry.counter("device_mirror_incremental_errors").value
    before_incr = _incr_count()
    assert mirror.ensure_fresh(store)
    assert registry.counter("device_mirror_incremental_errors").value \
        == before_err
    assert _incr_count() == before_incr + 1
    _assert_equivalent(store, mirror)
    # and appends after the pad continue incrementally + correctly
    full = counter_batch(10, 90, start_ms=START)
    k = full.timestamps >= START + 60 * 10_000
    sh.ingest(RecordBatch(full.schema, full.part_keys, full.part_idx[k],
                          full.timestamps[k],
                          {kk: v[k] for kk, v in full.columns.items()},
                          full.bucket_les), offset=1)
    assert mirror.ensure_fresh(store)
    _assert_equivalent(store, mirror)
