"""Columnar ingest pipeline: grid fast path vs per-record equivalence.

The PR contract: `shard.ingest_columns` (and the grid-shape detection in
`shard.ingest`) must be observationally identical to flat per-record
ingest — same stored cells, same encoded chunks at flush, same query
results — while never running per-row Python on the append path.
"""
import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.ingest.generator import (counter_batch, gauge_part_keys,
                                         histogram_batch)
from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                           LocalDiskMetaStore)

START = 1_600_000_000_000


def _grid_data(rng, S, k, jitter=True):
    ts = START + np.arange(k, dtype=np.int64)[None, :] * 10_000 \
        + np.zeros((S, 1), dtype=np.int64)
    if jitter:
        ts = ts + rng.integers(0, 3, size=(S, k))
        ts = np.sort(ts, axis=1) + np.arange(k, dtype=np.int64)[None, :]
    vals = rng.normal(100, 10, size=(S, k))
    return ts, vals


def _record_major_batch(schema, keys, ts2d, vals2d):
    """The SAME samples flattened record-major (sample j of every series,
    then sample j+1 ...) — deliberately NOT the grid layout, so this
    exercises the argsort/cumcount flat path."""
    S, k = ts2d.shape
    part_idx = np.tile(np.arange(S, dtype=np.int32), k)
    ts = ts2d.T.reshape(-1)
    vals = vals2d.T.reshape(-1)
    return RecordBatch(schema, keys, part_idx, ts, {"count": vals})


def test_columnar_matches_per_record_cells(rng):
    S, k = 300, 9
    base = counter_batch(S, 1, start_ms=START)
    ts2d, vals2d = _grid_data(rng, S, k)

    ms_a = TimeSeriesMemStore()
    sh_a = ms_a.setup("a", 0)
    n_a = sh_a.ingest_columns("prom-counter", base.part_keys, ts2d,
                              {"count": vals2d})
    ms_b = TimeSeriesMemStore()
    sh_b = ms_b.setup("b", 0)
    n_b = sh_b.ingest(_record_major_batch(base.schema, base.part_keys,
                                          ts2d, vals2d))
    assert n_a == n_b == S * k
    st_a, st_b = sh_a.stores["prom-counter"], sh_b.stores["prom-counter"]
    np.testing.assert_array_equal(st_a.counts[:S], st_b.counts[:S])
    np.testing.assert_array_equal(st_a.ts[:S, :k], st_b.ts[:S, :k])
    np.testing.assert_array_equal(st_a.cols["count"][:S, :k],
                                  st_b.cols["count"][:S, :k])


def test_grid_shaped_record_batch_detected(rng):
    """A grid-shaped RecordBatch through plain shard.ingest must produce
    the same store state as ingest_columns of the matrices (the detection
    fast path), including when later batches extend earlier ones."""
    S, k = 200, 4
    base = counter_batch(S, 1, start_ms=START)
    ms_a = TimeSeriesMemStore()
    sh_a = ms_a.setup("a", 0)
    ms_b = TimeSeriesMemStore()
    sh_b = ms_b.setup("b", 0)
    for i in range(3):
        ts2d, vals2d = _grid_data(rng, S, k, jitter=False)
        ts2d = ts2d + i * k * 10_000
        vals2d = vals2d + i
        sh_a.ingest_columns("prom-counter", base.part_keys, ts2d,
                            {"count": vals2d}, offset=i)
        batch = RecordBatch.from_grid(base.schema, base.part_keys, ts2d,
                                      {"count": vals2d})
        assert sh_a._grid_samples(batch) == k
        sh_b.ingest(batch, offset=i)
    st_a, st_b = sh_a.stores["prom-counter"], sh_b.stores["prom-counter"]
    np.testing.assert_array_equal(st_a.counts[:S], st_b.counts[:S])
    np.testing.assert_array_equal(st_a.ts[:S, :3 * k], st_b.ts[:S, :3 * k])
    np.testing.assert_array_equal(st_a.cols["count"][:S, :3 * k],
                                  st_b.cols["count"][:S, :3 * k])
    assert sh_a.ingested_offset == sh_b.ingested_offset == 2


def test_columnar_same_chunks_and_query_results(rng, tmp_path):
    """End-to-end: flush both pipelines to disk and compare the encoded
    chunk payloads byte-for-byte, then compare PromQL results."""
    from filodb_tpu.query.engine import QueryEngine

    S, k = 64, 120
    base = counter_batch(S, 1, start_ms=START)
    ts2d, _ = _grid_data(rng, S, k, jitter=False)
    vals2d = np.cumsum(rng.exponential(5.0, size=(S, k)), axis=1)

    results = {}
    chunks = {}
    for name, columnar in (("colmnr", True), ("record", False)):
        store_dir = str(tmp_path / name)
        ms = TimeSeriesMemStore(column_store=LocalDiskColumnStore(store_dir),
                                meta_store=LocalDiskMetaStore(store_dir))
        sh = ms.setup("ds", 0)
        if columnar:
            sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                              {"count": vals2d}, offset=1)
        else:
            sh.ingest(_record_major_batch(base.schema, base.part_keys,
                                          ts2d, vals2d), offset=1)
        sh.flush_all_groups()
        got = {}
        for info in sh.partitions:
            css = list(ms.column_store.read_chunks(
                "ds", 0, info.part_key, START, START + k * 10_000))
            got[info.part_key.to_bytes()] = [
                (cs.info.num_rows, cs.info.start_time_ms,
                 cs.info.end_time_ms,
                 {c: (col.kind, col.payload, col.base, col.slope)
                  for c, col in cs.columns.items()})
                for cs in css]
        chunks[name] = got
        eng = QueryEngine("ds", ms)
        s = START // 1000
        res = eng.query_range('sum by (_ns_)(rate(request_total[5m]))',
                              s + 600, 60, s + k * 10)
        assert res.error is None
        results[name] = sorted(
            (str(key), np.asarray(vs).tolist())
            for key, _, vs in res.series())

    assert chunks["colmnr"] == chunks["record"]
    assert results["colmnr"] == results["record"]


def test_columnar_out_of_order_drops_match_flat(rng):
    """Rows violating monotonicity degrade per-row to the flat path's
    per-sample drop semantics; clean rows still land."""
    S, k = 50, 5
    base = counter_batch(S, 1, start_ms=START)
    ts2d, vals2d = _grid_data(rng, S, k, jitter=False)

    ms_a = TimeSeriesMemStore()
    sh_a = ms_a.setup("a", 0)
    ms_b = TimeSeriesMemStore()
    sh_b = ms_b.setup("b", 0)
    for sh, col in ((sh_a, True), (sh_b, False)):
        if col:
            sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                              {"count": vals2d})
        else:
            sh.ingest(_record_major_batch(base.schema, base.part_keys,
                                          ts2d, vals2d))
    # second round: half the rows re-send the SAME timestamps (drop),
    # half advance cleanly
    ts2 = ts2d.copy()
    ts2[::2] += k * 10_000
    for sh, col in ((sh_a, True), (sh_b, False)):
        if col:
            n = sh.ingest_columns("prom-counter", base.part_keys, ts2,
                                  {"count": vals2d})
        else:
            n = sh.ingest(_record_major_batch(base.schema, base.part_keys,
                                              ts2, vals2d))
        assert n == (S // 2) * k
    st_a, st_b = sh_a.stores["prom-counter"], sh_b.stores["prom-counter"]
    np.testing.assert_array_equal(st_a.counts[:S], st_b.counts[:S])
    np.testing.assert_array_equal(st_a.ts[:S, :2 * k], st_b.ts[:S, :2 * k])
    assert sh_a.stats.rows_dropped == sh_b.stats.rows_dropped


def test_columnar_histograms(rng):
    S, k, B = 24, 6, 8
    hb = histogram_batch(S, 1, start_ms=START)
    ts2d = START + np.arange(k, dtype=np.int64)[None, :] * 10_000 \
        + np.zeros((S, 1), dtype=np.int64)
    hist = rng.poisson(3.0, size=(S, k, B)).cumsum(axis=1).cumsum(axis=2) \
        .astype(np.float64)
    cnt = hist[:, :, -1].copy()
    sm = cnt * 3.0
    les = np.asarray(hb.bucket_les)

    ms_a = TimeSeriesMemStore()
    sh_a = ms_a.setup("a", 0)
    n = sh_a.ingest_columns("prom-histogram", hb.part_keys, ts2d,
                            {"sum": sm, "count": cnt, "h": hist},
                            bucket_les=les)
    assert n == S * k
    ms_b = TimeSeriesMemStore()
    sh_b = ms_b.setup("b", 0)
    flat = RecordBatch.from_grid(hb.schema, hb.part_keys, ts2d,
                                 {"sum": sm, "count": cnt, "h": hist},
                                 bucket_les=les)
    assert sh_b.ingest(flat) == S * k
    st_a, st_b = sh_a.stores["prom-histogram"], sh_b.stores["prom-histogram"]
    np.testing.assert_array_equal(st_a.cols["h"][:S, :k], hist)
    np.testing.assert_array_equal(st_a.cols["h"][:S, :k],
                                  st_b.cols["h"][:S, :k])


def test_duplicate_keys_fall_back_correctly(rng):
    """Duplicate part keys alias one pid — the grid path must detect this
    and degrade to the flat path's cumcount semantics, appending all
    samples of the duplicated series in order."""
    keys = gauge_part_keys(4)
    dup_keys = [keys[0], keys[1], keys[0], keys[2]]     # keys[0] twice
    base = counter_batch(1, 1, start_ms=START)
    ts2d = START + (np.arange(2, dtype=np.int64)[None, :] * 10_000
                    + np.asarray([[0], [0], [20_000], [0]], dtype=np.int64))
    vals2d = rng.normal(size=(4, 2))
    ms = TimeSeriesMemStore()
    sh = ms.setup("a", 0)
    n = sh.ingest_columns("prom-counter", dup_keys, ts2d, {"count": vals2d})
    assert n == 8
    st = sh.stores["prom-counter"]
    # the duplicated series holds all 4 of its samples, time-ascending
    row0 = sh.partitions[0].row
    assert st.counts[row0] == 4
    assert (np.diff(st.ts[row0, :4]) > 0).all()


def test_quota_hole_retry_uses_right_first_ts(rng):
    """A quota-rejected series leaves a -1 hole mid-table; when a later
    batch retries it (quota raised), partition creation must read THAT
    key's first timestamp, not a positionally-compacted array (which
    either crashes or steals another series' start time)."""
    from filodb_tpu.core.ratelimit import QuotaReachedException

    S, k = 8, 3
    base = counter_batch(S, 1, start_ms=START)
    ms = TimeSeriesMemStore()
    sh = ms.setup("a", 0)

    class OneShotQuota:
        def __init__(self, reject_at):
            self.reject_at = reject_at
            self.calls = 0

        def series_created(self, key):
            self.calls += 1
            if self.calls == self.reject_at:
                raise QuotaReachedException(key, 1)

        def series_stopped(self, key):
            pass

        def flush(self):
            pass

    sh.cardinality_tracker = OneShotQuota(reject_at=6)   # key index 5
    ts2d, vals2d = _grid_data(rng, S, k, jitter=False)
    n = sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                          {"count": vals2d})
    assert n == (S - 1) * k and sh.stats.quota_dropped == 1
    # retry batch: the hole at index 5 resolves now, with ITS start time
    ts2 = ts2d + k * 10_000
    n2 = sh.ingest_columns("prom-counter", base.part_keys, ts2,
                           {"count": vals2d})
    assert n2 == S * k
    pid = sh.part_set[base.part_keys[5].to_bytes()]
    assert sh.index.start_time(pid) == int(ts2[5, 0])


def test_grid_fallback_eviction_repositions_clean_rows(rng):
    """A mixed batch whose dirty rows trigger store-wide eviction through
    the flat fallback must re-derive the clean rows' append positions —
    stale positions would land outside the live window (data loss)."""
    from filodb_tpu.core.blockstore import DenseSeriesStore
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS

    store = DenseSeriesStore(DEFAULT_SCHEMAS["prom-counter"],
                             initial_series=2, initial_time=16,
                             max_time_cap=64)
    r0, r1 = store.new_row(), store.new_row()
    # row1 near the cap, row0 short; everything sealed (evictable)
    ts1 = START + np.arange(60, dtype=np.int64) * 10
    store.append_batch(np.full(60, r1, dtype=np.int64), ts1,
                       {"count": np.ones(60)})
    ts0 = START + np.arange(10, dtype=np.int64) * 10
    store.append_batch(np.full(10, r0, dtype=np.int64), ts0,
                       {"count": np.ones(10)})
    store.mark_sealed(r0, 10)
    store.mark_sealed(r1, 60)
    # grid: row1 out-of-order (re-sends old ts -> flat fallback; its big
    # appended tail forces eviction), row0 clean and past its last ts
    kk = 6
    grid_ts = np.stack([ts0[-1] + (np.arange(kk, dtype=np.int64) + 1) * 10,
                        ts1[0] + np.arange(kk, dtype=np.int64)])
    grid_vals = np.full((2, kk), 7.0)
    n = store.append_grid(np.asarray([r0, r1]), grid_ts,
                          {"count": grid_vals})
    assert n >= kk                      # row0's samples all landed
    c0 = int(store.counts[r0])
    got = store.ts[r0, :c0]
    # row0's visible window must END with the new samples, no PAD holes
    assert (got < np.iinfo(np.int64).max).all()
    assert int(got[-1]) == int(grid_ts[0, -1])
    assert np.isin(grid_ts[0], got).all()


def test_ingest_columns_validates_shape():
    base = counter_batch(4, 1, start_ms=START)
    ms = TimeSeriesMemStore()
    sh = ms.setup("a", 0)
    with pytest.raises(ValueError):
        sh.ingest_columns("prom-counter", base.part_keys,
                          np.zeros(8, dtype=np.int64), {"count": np.zeros(8)})
