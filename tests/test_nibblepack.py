"""NibblePack + delta-delta + XOR codec roundtrip and format tests
(models the reference's EncodingPropertiesTest / NibblePackTest property suite,
ref: memory/src/test/.../format/NibblePackTest.scala)."""
import numpy as np
import pytest

from filodb_tpu.memory import nibblepack as nbp
from filodb_tpu.memory.chunks import (
    encode_chunkset, decode_chunkset, decode_column, encode_ts_column)
from filodb_tpu.memory.histogram import (
    HistogramBuckets, encode_hist_matrix, decode_hist_matrix, default_buckets)


def test_pack_all_zeros_is_one_byte_per_group():
    data = nbp.pack(np.zeros(64, dtype=np.uint64))
    assert data == bytes(8)  # 8 groups x 1 bitmask byte


def test_pack_spec_example():
    # doc/compression.md:77-90 worked example: two 3-nibble values
    vals = np.array([0x0000_0000_0012_3000, 0x0000_0000_0045_6000], dtype=np.uint64)
    data = nbp.pack(vals)
    assert data[0] == 0b11               # two nonzero values
    assert data[1] == (3 | ((3 - 1) << 4))  # 3 trailing zero nibbles, 3 nibbles
    assert data[2:5] == bytes([0x23, 0x61, 0x45])


@pytest.mark.parametrize("n", [0, 1, 7, 8, 9, 100, 1000])
def test_pack_roundtrip_random(n, rng):
    vals = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    # mix in zeros and small values
    if n > 4:
        vals[::3] = 0
        vals[1::3] = rng.integers(0, 16, size=len(vals[1::3]), dtype=np.uint64)
    out = nbp.unpack(nbp.pack(vals), n)
    np.testing.assert_array_equal(out, vals)


def test_zigzag_roundtrip(rng):
    v = rng.integers(-(1 << 62), 1 << 62, size=257, dtype=np.int64)
    np.testing.assert_array_equal(nbp.zigzag_decode(nbp.zigzag_encode(v)), v)
    np.testing.assert_array_equal(nbp.zigzag_encode(np.array([0, -1, 1, -2, 2])),
                                  np.array([0, 1, 2, 3, 4], dtype=np.uint64))


def test_timestamps_const_slope_is_tiny():
    ts = np.arange(0, 720 * 10_000, 10_000, dtype=np.int64) + 1_600_000_000_000
    base, slope, payload = nbp.pack_timestamps(ts)
    assert slope == 10_000
    assert len(payload) == 90  # 720/8 groups, all-zero deltas -> 1 byte each
    np.testing.assert_array_equal(nbp.unpack_timestamps(base, slope, payload, len(ts)), ts)


def test_timestamps_jittered_roundtrip(rng):
    ts = (np.arange(500, dtype=np.int64) * 10_000
          + rng.integers(-200, 200, size=500)) + 1_700_000_000_000
    ts.sort()
    base, slope, payload = nbp.pack_timestamps(ts)
    np.testing.assert_array_equal(nbp.unpack_timestamps(base, slope, payload, 500), ts)


def test_doubles_xor_roundtrip_with_nans(rng):
    vals = rng.normal(100, 5, size=300)
    vals[::17] = np.nan
    out = nbp.unpack_f64_xor(nbp.pack_f64_xor(vals), 300)
    np.testing.assert_array_equal(out.view(np.uint64), vals.view(np.uint64))


def test_hist_matrix_roundtrip(rng):
    raw = rng.integers(0, 50, size=(64, 8))
    mat = np.cumsum(np.cumsum(raw, axis=0), axis=1)  # cumulative in both axes
    out = decode_hist_matrix(encode_hist_matrix(mat), 64, 8)
    np.testing.assert_array_equal(out, mat)


def test_geometric_buckets():
    b = default_buckets()
    assert b.les == (2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
    binf = HistogramBuckets.geometric(1.0, 2.0, 4)
    assert binf.les[-1] == float("inf")


def test_chunkset_roundtrip(rng):
    n = 250
    ts = np.arange(n, dtype=np.int64) * 15_000 + 1_650_000_000_000
    gauge = rng.normal(50, 10, size=n)
    counter = np.cumsum(rng.exponential(5, size=n))
    cs = encode_chunkset(ts, {"value": gauge, "count": counter},
                         {"value": "double", "count": "double"},
                         ingestion_time_ms=123)
    assert cs.info.num_rows == n
    assert cs.info.start_time_ms == int(ts[0])
    assert cs.info.end_time_ms == int(ts[-1])
    cols = decode_chunkset(cs)
    np.testing.assert_array_equal(cols["timestamp"], ts)
    np.testing.assert_array_equal(cols["value"], gauge)
    np.testing.assert_array_equal(cols["count"], counter)
    # compression sanity: regular timestamps ~0.2 B/sample
    assert cs.columns["timestamp"].nbytes < n


def test_compression_ratio_counter():
    # smooth counters should compress well under XOR+NibblePack
    n = 720
    vals = np.cumsum(np.full(n, 3.0))
    payload = nbp.pack_f64_xor(vals)
    assert len(payload) < n * 8 * 0.8
