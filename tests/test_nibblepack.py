"""NibblePack + delta-delta + XOR codec roundtrip and format tests
(models the reference's EncodingPropertiesTest / NibblePackTest property suite,
ref: memory/src/test/.../format/NibblePackTest.scala)."""
import numpy as np
import pytest

from filodb_tpu.memory import nibblepack as nbp
from filodb_tpu.memory.chunks import (
    encode_chunkset, decode_chunkset, decode_column, encode_ts_column)
from filodb_tpu.memory.histogram import (
    HistogramBuckets, encode_hist_matrix, decode_hist_matrix, default_buckets)


def test_pack_all_zeros_is_one_byte_per_group():
    data = nbp.pack(np.zeros(64, dtype=np.uint64))
    assert data == bytes(8)  # 8 groups x 1 bitmask byte


def test_pack_spec_example():
    # doc/compression.md:77-90 worked example: two 3-nibble values
    vals = np.array([0x0000_0000_0012_3000, 0x0000_0000_0045_6000], dtype=np.uint64)
    data = nbp.pack(vals)
    assert data[0] == 0b11               # two nonzero values
    assert data[1] == (3 | ((3 - 1) << 4))  # 3 trailing zero nibbles, 3 nibbles
    assert data[2:5] == bytes([0x23, 0x61, 0x45])


@pytest.mark.parametrize("n", [0, 1, 7, 8, 9, 100, 1000])
def test_pack_roundtrip_random(n, rng):
    vals = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    # mix in zeros and small values
    if n > 4:
        vals[::3] = 0
        vals[1::3] = rng.integers(0, 16, size=len(vals[1::3]), dtype=np.uint64)
    out = nbp.unpack(nbp.pack(vals), n)
    np.testing.assert_array_equal(out, vals)


def test_zigzag_roundtrip(rng):
    v = rng.integers(-(1 << 62), 1 << 62, size=257, dtype=np.int64)
    np.testing.assert_array_equal(nbp.zigzag_decode(nbp.zigzag_encode(v)), v)
    np.testing.assert_array_equal(nbp.zigzag_encode(np.array([0, -1, 1, -2, 2])),
                                  np.array([0, 1, 2, 3, 4], dtype=np.uint64))


def test_timestamps_const_slope_is_tiny():
    ts = np.arange(0, 720 * 10_000, 10_000, dtype=np.int64) + 1_600_000_000_000
    base, slope, payload = nbp.pack_timestamps(ts)
    assert slope == 10_000
    assert len(payload) == 90  # 720/8 groups, all-zero deltas -> 1 byte each
    np.testing.assert_array_equal(nbp.unpack_timestamps(base, slope, payload, len(ts)), ts)


def test_timestamps_jittered_roundtrip(rng):
    ts = (np.arange(500, dtype=np.int64) * 10_000
          + rng.integers(-200, 200, size=500)) + 1_700_000_000_000
    ts.sort()
    base, slope, payload = nbp.pack_timestamps(ts)
    np.testing.assert_array_equal(nbp.unpack_timestamps(base, slope, payload, 500), ts)


def test_doubles_xor_roundtrip_with_nans(rng):
    vals = rng.normal(100, 5, size=300)
    vals[::17] = np.nan
    out = nbp.unpack_f64_xor(nbp.pack_f64_xor(vals), 300)
    np.testing.assert_array_equal(out.view(np.uint64), vals.view(np.uint64))


def test_hist_matrix_roundtrip(rng):
    raw = rng.integers(0, 50, size=(64, 8))
    mat = np.cumsum(np.cumsum(raw, axis=0), axis=1)  # cumulative in both axes
    out = decode_hist_matrix(encode_hist_matrix(mat), 64, 8)
    np.testing.assert_array_equal(out, mat)


def test_geometric_buckets():
    b = default_buckets()
    assert b.les == (2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
    binf = HistogramBuckets.geometric(1.0, 2.0, 4)
    assert binf.les[-1] == float("inf")


def test_chunkset_roundtrip(rng):
    n = 250
    ts = np.arange(n, dtype=np.int64) * 15_000 + 1_650_000_000_000
    gauge = rng.normal(50, 10, size=n)
    counter = np.cumsum(rng.exponential(5, size=n))
    cs = encode_chunkset(ts, {"value": gauge, "count": counter},
                         {"value": "double", "count": "double"},
                         ingestion_time_ms=123)
    assert cs.info.num_rows == n
    assert cs.info.start_time_ms == int(ts[0])
    assert cs.info.end_time_ms == int(ts[-1])
    cols = decode_chunkset(cs)
    np.testing.assert_array_equal(cols["timestamp"], ts)
    np.testing.assert_array_equal(cols["value"], gauge)
    np.testing.assert_array_equal(cols["count"], counter)
    # compression sanity: regular timestamps ~0.2 B/sample
    assert cs.columns["timestamp"].nbytes < n


def test_compression_ratio_counter():
    # smooth counters should compress well under XOR+NibblePack
    n = 720
    vals = np.cumsum(np.full(n, 3.0))
    payload = nbp.pack_f64_xor(vals)
    assert len(payload) < n * 8 * 0.8


# ---------------------------------------------------------------------------
# three-way implementation parity: pure-Python reference vs vectorized NumPy
# vs the C lib (when built).  The vectorized codec is the default fallback,
# so every byte must match the spec implementation — including the error
# contract on truncated input.


def _fuzz_values(rng, n: int, kind: int) -> np.ndarray:
    if kind == 0:                              # dense high-entropy
        return rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    if kind == 1:                              # all-zero groups
        return np.zeros(n, dtype=np.uint64)
    if kind == 2:                              # max-nibble values
        return np.full(n, 0xFFFF_FFFF_FFFF_FFFF, dtype=np.uint64)
    if kind == 3:                              # one nibble, sliding position
        return (rng.integers(0, 16, size=n, dtype=np.uint64)
                << rng.integers(0, 60, size=n, dtype=np.uint64))
    if kind == 4:                              # delta-delta-like small codes
        return rng.integers(0, 20, size=n, dtype=np.uint64)
    if kind == 5:                              # mid-width values
        return rng.integers(0, 1 << 28, size=n, dtype=np.uint64)
    vals = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    if n:                                      # mixed zeros / nonzeros
        vals[rng.random(n) < 0.5] = 0
    return vals


@pytest.mark.parametrize("kind", range(7))
def test_three_way_parity_fuzz(kind, rng):
    for trial in range(40):
        n = int(rng.integers(0, 300)) if trial < 25 \
            else int(rng.integers(300, 9000))
        vals = _fuzz_values(rng, n, kind)
        ref = nbp._pack_py(vals)
        assert nbp._pack_vec(vals) == ref, (kind, trial, n)
        if nbp._native is not None:
            assert nbp._native.nibble_pack(vals) == ref, (kind, trial, n)
        out_py = nbp._unpack_py(ref, n)
        np.testing.assert_array_equal(out_py, vals)
        np.testing.assert_array_equal(nbp._unpack_vec(ref, n), out_py)
        if nbp._native is not None:
            np.testing.assert_array_equal(
                nbp._native.nibble_unpack(ref, n), out_py)


@pytest.mark.parametrize("kind", [0, 3, 4, 6])
def test_truncated_input_parity(kind, rng):
    """Every implementation must reject a truncated stream with
    ValueError at exactly the same prefixes — a node decoding with the
    C lib and one on the NumPy fallback must never disagree."""
    for trial in range(15):
        n = int(rng.integers(8, 2000))
        vals = _fuzz_values(rng, n, kind)
        data = nbp._pack_py(vals)
        if len(data) < 3:
            continue
        for cut_at in {0, 1, len(data) // 2, len(data) - 1}:
            cut = data[:cut_at]
            outcomes = []
            for fn in (nbp._unpack_py, nbp._unpack_vec) + (
                    (nbp._native.nibble_unpack,) if nbp._native else ()):
                try:
                    fn(cut, n)
                    outcomes.append("ok")
                except ValueError:
                    outcomes.append("err")
            assert len(set(outcomes)) == 1, (kind, trial, cut_at, outcomes)


def _best_of(fn, reps=5):
    import time
    b = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        b = min(b, time.perf_counter() - t0)
    return b


def test_vectorized_speedup_on_production_shapes(rng):
    """Acceptance bound for this PR: the vectorized codec is >= 10x the
    pure-Python reference on 64k-value arrays of the shapes the flush
    path actually produces (delta-delta'd timestamps — mostly zero — and
    zigzag'd integral counter deltas).  The 2-core CI box's scheduler
    jitter swings single measurements ~2x in both directions, so each
    attempt takes best-of-5 per implementation and the test passes on
    the best of 4 attempts (quiet-box reference numbers live in
    BASELINE.md)."""
    n = 65_536
    shapes = {
        "ts_const_slope": np.zeros(n, dtype=np.uint64),
        "counter_dd": nbp.zigzag_encode(
            rng.integers(-40, 40, size=n).astype(np.int64)),
    }

    ratios = []
    for _ in range(4):
        t_py = t_vec = 0.0
        for vals in shapes.values():
            data = nbp._pack_py(vals)
            nbp._pack_vec(vals)                # warm allocations
            nbp._unpack_vec(data, n)
            t_py += _best_of(lambda: nbp._pack_py(vals))
            t_py += _best_of(lambda: nbp._unpack_py(data, n))
            t_vec += _best_of(lambda: nbp._pack_vec(vals))
            t_vec += _best_of(lambda: nbp._unpack_vec(data, n))
        ratios.append(t_py / t_vec)
        if ratios[-1] >= 10.0:
            return
    raise AssertionError(
        f"vectorized codec only {max(ratios):.1f}x the Python reference "
        f"across 4 attempts ({['%.1f' % r for r in ratios]})")


def test_vectorized_faster_on_adversarial_dense(rng):
    """Dense high-entropy data (no zeros, ~10 nibbles/value) is the
    worst case for the vectorized layout resolution — still must beat
    the Python loop by a wide margin."""
    n = 65_536
    vals = rng.integers(0, 1 << 40, size=n, dtype=np.uint64)
    data = nbp._pack_py(vals)
    nbp._pack_vec(vals)
    nbp._unpack_vec(data, n)

    ratios = []
    for _ in range(3):
        ratios.append((_best_of(lambda: nbp._pack_py(vals))
                       + _best_of(lambda: nbp._unpack_py(data, n)))
                      / (_best_of(lambda: nbp._pack_vec(vals))
                         + _best_of(lambda: nbp._unpack_vec(data, n))))
        if ratios[-1] >= 2.0:
            return
    raise AssertionError(f"dense-input speedup only {max(ratios):.1f}x")
