"""Network chunk service: the ColumnStore/MetaStore traits over TCP
(ref: cassandra/.../columnstore/CassandraColumnStore.scala:53-80 — the
reference's store is a remote service shared by all nodes)."""
import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.store import InMemoryMetaStore
from filodb_tpu.ingest.generator import gauge_batch
from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                           LocalDiskMetaStore)
from filodb_tpu.persist.netstore import (ChunkServiceServer,
                                         RemoteColumnStore, RemoteMetaStore)

START = 1_600_000_020_000
T = 240


@pytest.fixture()
def service(tmp_path):
    srv = ChunkServiceServer(LocalDiskColumnStore(str(tmp_path / "store")),
                             LocalDiskMetaStore(str(tmp_path / "store"))
                             ).start()
    yield srv
    srv.stop()


def _remote(service):
    host, port = service.address
    return RemoteColumnStore(host, port), RemoteMetaStore(host, port)


def test_column_store_contract_roundtrip(service):
    remote, _ = _remote(service)
    local = service.column_store

    # flush a memstore THROUGH the network store
    ms = TimeSeriesMemStore(column_store=remote,
                            meta_store=InMemoryMetaStore())
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(8, T, start_ms=START))
    sh.flush_all_groups()

    # part keys + chunks land in the backing store and read back
    # identically over the wire
    recs_local = local.read_part_keys("prometheus", 0)
    recs_remote = remote.read_part_keys("prometheus", 0)
    assert len(recs_local) == len(recs_remote) == 8
    assert ({r.part_key.to_bytes() for r in recs_local}
            == {r.part_key.to_bytes() for r in recs_remote})

    rec = recs_remote[0]
    a = local.read_chunks("prometheus", 0, rec.part_key, 0, 1 << 62)
    b = remote.read_chunks("prometheus", 0, rec.part_key, 0, 1 << 62)
    assert len(a) == len(b) == 1
    assert a[0].info.num_rows == b[0].info.num_rows == T
    assert a[0].columns.keys() == b[0].columns.keys()
    for name in a[0].columns:
        assert a[0].columns[name].payload == b[0].columns[name].payload

    # ingestion-time scan over the wire
    hits = list(remote.scan_chunks_by_ingestion_time(
        "prometheus", 0, 0, 1 << 62))
    assert len(hits) == 8
    pk, schema_name, cs = hits[0]
    assert schema_name and cs.info.num_rows == T
    assert remote.num_chunksets("prometheus", 0) == 8

    # delete part keys over the wire
    assert remote.delete_part_keys("prometheus", 0,
                                   [rec.part_key]) == 1
    assert len(remote.read_part_keys("prometheus", 0)) == 7


def test_meta_store_checkpoints(service):
    _, meta = _remote(service)
    assert meta.read_checkpoints("ds", 1) == {}
    meta.write_checkpoint("ds", 1, 0, 42)
    meta.write_checkpoint("ds", 1, 3, 99)
    assert meta.read_checkpoints("ds", 1) == {0: 42, 3: 99}
    assert meta.read_earliest_checkpoint("ds", 1) == 42
    assert meta.read_highest_checkpoint("ds", 1) == 99


def test_odp_through_network_store(service):
    """Flush + evict, then a query-shaped gather pages chunks back in
    through the TCP store (the cross-machine ODP the reference gets from
    Cassandra)."""
    remote, _ = _remote(service)
    ms = TimeSeriesMemStore(column_store=remote,
                            meta_store=InMemoryMetaStore())
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(6, T, start_ms=START))
    sh.flush_all_groups()

    # a FRESH memstore over the same remote store: index bootstrap +
    # on-demand paging, nothing local
    ms2 = TimeSeriesMemStore(column_store=remote,
                             meta_store=InMemoryMetaStore())
    sh2 = ms2.setup("prometheus", 0)
    assert sh2.recover_index() == 6
    from filodb_tpu.core.index import Equals
    res = sh2.lookup_partitions([Equals("_metric_", "heap_usage")],
                                START, START + T * 10_000)
    pids = res.pids_by_schema[res.first_schema]
    paged = sh2.ensure_paged_pids(res.first_schema, pids, START,
                                  START + T * 10_000)
    assert paged == 6 * T, "every sample should page in over TCP"
    ts, cols, counts, _ = sh2.gather_series(
        res.parts_by_schema[res.first_schema])
    assert counts.sum() == 6 * T
    assert np.isfinite(cols["value"]).all()


def test_remote_store_reconnects_after_service_restart(tmp_path):
    root = str(tmp_path / "store")
    srv = ChunkServiceServer(LocalDiskColumnStore(root),
                             LocalDiskMetaStore(root)).start()
    host, port = srv.address
    remote = RemoteColumnStore(host, port)
    ms = TimeSeriesMemStore(column_store=remote,
                            meta_store=InMemoryMetaStore())
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(4, 60, start_ms=START))
    sh.flush_all_groups()
    assert len(remote.read_part_keys("prometheus", 0)) == 4
    # service restarts on the same port (new process in production); the
    # pooled client connection reconnects transparently
    srv.stop()
    srv2 = ChunkServiceServer(LocalDiskColumnStore(root),
                              LocalDiskMetaStore(root),
                              host=host, port=port).start()
    try:
        assert len(remote.read_part_keys("prometheus", 0)) == 4
    finally:
        srv2.stop()


def test_retried_writes_are_idempotent(service):
    """A lost-reply retry re-sends write_chunks; the store dedupes by
    chunk id so reads never see doubled chunks (at-least-once delivery
    with exactly-once effect)."""
    remote, _ = _remote(service)
    ms = TimeSeriesMemStore(column_store=remote,
                            meta_store=InMemoryMetaStore())
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(3, 60, start_ms=START))
    sh.flush_all_groups()
    rec = remote.read_part_keys("prometheus", 0)[0]
    chunks = remote.read_chunks("prometheus", 0, rec.part_key, 0, 1 << 62)
    assert len(chunks) == 1
    # simulate the duplicated retry: send the identical chunkset again
    remote.write_chunks("prometheus", 0, rec.part_key, chunks,
                        rec.schema_name)
    assert len(remote.read_chunks("prometheus", 0, rec.part_key, 0,
                                  1 << 62)) == 1
    # and the duplicate survives an index rebuild from the on-disk log
    fresh = LocalDiskColumnStore(service.column_store.root)
    assert len(fresh.read_chunks("prometheus", 0, rec.part_key, 0,
                                 1 << 62)) == 1
