"""Distributed mesh execution tests on the 8-virtual-device CPU mesh.

The correctness oracle is the single-process engine over the same data —
the analogue of the reference's multi-JVM specs asserting cluster results
match (ref: standalone/src/multi-jvm/.../IngestionAndRecoverySpec.scala).
"""
import numpy as np
import pytest

import jax

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.index import Equals
from filodb_tpu.ingest.generator import counter_batch, gauge_batch
from filodb_tpu.ops.timewindow import make_window_ends
from filodb_tpu.parallel.mesh import (MeshExecutor, make_mesh, pack_shards,
                                      device_put_packed,
                                      distributed_window_agg,
                                      distributed_window_raw)
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper, SpreadProvider
from filodb_tpu.query.engine import QueryEngine

from test_query_engine import _mk_engine, START_MS, START_S, NUM_SAMPLES

QEND_S = START_S + 3600
STEP_S = 60


def _mk_store(num_shards=4, n_series=64):
    ms = TimeSeriesMemStore()
    mapper = ShardMapper(num_shards)
    for s in range(num_shards):
        ms.setup("prometheus", s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", s, "local"))
    batch = counter_batch(n_series, NUM_SAMPLES, start_ms=START_MS)
    shard_of_key = np.asarray([
        mapper.ingestion_shard(pk.shard_key_hash(), pk.partition_hash(), 2)
        for pk in batch.part_keys])
    for s in range(num_shards):
        keep = shard_of_key[batch.part_idx] == s
        if keep.any():
            sub = RecordBatch(batch.schema, batch.part_keys,
                              batch.part_idx[keep], batch.timestamps[keep],
                              {k: v[keep] for k, v in batch.columns.items()},
                              batch.bucket_les)
            ms.get_shard("prometheus", s).ingest(sub)
    return ms, mapper


@pytest.fixture(scope="module")
def mesh42():
    return make_mesh(4, 2, devices=jax.devices("cpu")[:8])


@pytest.fixture(scope="module")
def store4():
    return _mk_store(num_shards=4)


def _engine_result(ms, mapper, promql):
    eng = QueryEngine("prometheus", ms, mapper, SpreadProvider(default_spread=2))
    res = eng.query_range(promql, START_S + 600, STEP_S, QEND_S)
    assert res.error is None, res.error
    return res


def _mesh_result(ms, mesh, agg_op, fn_name, by=(), range_ms=300_000):
    ex = MeshExecutor(ms, "prometheus", mesh)
    packed = ex.lookup_and_pack(
        [Equals("_metric_", "request_total"), Equals("_ws_", "demo"),
         Equals("_ns_", "App-0")],
        (START_S + 600) * 1000 - range_ms, QEND_S * 1000, by=by)
    wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                             STEP_S * 1000)
    # absolute ms: run_agg rebases onto the pack's offset base itself
    out, labels = ex.run_agg(packed, wends, range_ms=range_ms,
                             fn_name=fn_name, agg_op=agg_op)
    return out, labels


def test_mesh_sum_rate_matches_engine(store4, mesh42):
    ms, mapper = store4
    res = _engine_result(ms, mapper, 'sum(rate(request_total{_ws_="demo",_ns_="App-0"}[5m]))')
    out, labels = _mesh_result(ms, mesh42, "sum", "rate")
    assert out.shape[0] == 1 and not labels[0]
    got = out[0]
    rows = list(res.series())
    assert len(rows) == 1
    want = np.asarray(rows[0][2])
    valid = ~np.isnan(want)
    np.testing.assert_allclose(got[valid], want[valid], rtol=1e-9)
    assert np.isnan(got[~valid]).all()


@pytest.mark.parametrize("agg_op,fn", [("min", "min_over_time"),
                                       ("max", "max_over_time"),
                                       ("avg", "avg_over_time"),
                                       ("count", "last_over_time"),
                                       ("stddev", "sum_over_time")])
def test_mesh_aggs_match_engine(store4, mesh42, agg_op, fn):
    ms, mapper = store4
    res = _engine_result(
        ms, mapper,
        f'{agg_op}({fn}(request_total{{_ws_="demo",_ns_="App-0"}}[5m]))')
    out, _ = _mesh_result(ms, mesh42, agg_op, fn)
    want = np.asarray(next(res.series())[2])
    valid = ~np.isnan(want)
    np.testing.assert_allclose(out[0][valid], want[valid], rtol=1e-8)


def test_mesh_group_by(store4, mesh42):
    ms, mapper = store4
    res = _engine_result(
        ms, mapper, 'sum by (instance) (rate(request_total{_ws_="demo",_ns_="App-0"}[5m]))')
    out, labels = _mesh_result(ms, mesh42, "sum", "rate", by=("instance",))
    rows = list(res.series())
    assert len(labels) == len(rows)
    by_engine = {k.labels_dict.get("instance"): np.asarray(v)
                 for k, _, v in rows}
    for slot, lab in enumerate(labels):
        want = by_engine[lab["instance"]]
        valid = ~np.isnan(want)
        np.testing.assert_allclose(out[slot][valid], want[valid], rtol=1e-9)


def test_mesh_raw_path_shapes(mesh42):
    # 4 shards, 8 series each, tiny grid; raw result keeps sharded layout
    rng = np.random.default_rng(0)
    blocks = []
    for d in range(4):
        ts = np.cumsum(np.full((8, 100), 10_000, np.int64), axis=1)
        vals = rng.random((8, 100))
        labels = [{"instance": f"i{d}-{i}"} for i in range(8)]
        from filodb_tpu.ops.timewindow import to_offsets
        blocks.append((to_offsets(ts, np.full(8, 100), 0), vals, labels))
    packed = pack_shards(blocks)
    packed = device_put_packed(packed, mesh42)
    wends = np.arange(100_000, 1_000_001, 50_000, dtype=np.int32)
    # pad to multiple of time axis (2)
    if wends.shape[0] % 2:
        wends = np.concatenate([wends, wends[-1:] + 50_000])
    out = distributed_window_raw(mesh42, packed.ts_off, packed.values,
                                 jax.device_put(wends), range_ms=60_000,
                                 fn_name="sum_over_time")
    assert out.shape == (4, 8, wends.shape[0])
    assert np.isfinite(np.asarray(out)).any()


def test_mesh_empty_shard_contributes_nothing(mesh42):
    # shard 3 has no matching series: NaN rows must not poison the psum
    from filodb_tpu.ops.timewindow import to_offsets, PAD_TS
    ts = np.cumsum(np.full((4, 50), 10_000, np.int64), axis=1)
    vals = np.ones((4, 50))
    labels = [{"instance": f"i{i}"} for i in range(4)]
    blocks = [(to_offsets(ts, np.full(4, 50), 0), vals, labels)]
    for _ in range(3):
        blocks.append((np.full((1, 1), PAD_TS, np.int32),
                       np.full((1, 1), np.nan), []))
    packed = device_put_packed(pack_shards(blocks), mesh42)
    wends = np.asarray([200_000, 300_000, 400_000, 500_000], np.int32)
    out = distributed_window_agg(
        mesh42, packed.ts_off, packed.values, packed.group_ids,
        jax.device_put(wends), range_ms=100_000, fn_name="sum_over_time",
        agg_op="sum", num_groups=packed.num_groups)
    from filodb_tpu.ops import agg as agg_ops
    final = np.asarray(agg_ops.present("sum", out))
    # 4 series * 10 samples/window * 1.0 each = 40
    np.testing.assert_allclose(final[0], 40.0)


def test_mesh_fused_sum_rate_matches_general(store4, mesh42, monkeypatch):
    """The Pallas fused mesh path (shard_map + psum around the MXU kernel)
    must match the general distributed path and the single-process engine."""
    from filodb_tpu.utils.metrics import registry
    ms, mapper = store4
    range_ms = 300_000

    def run():
        ex = MeshExecutor(ms, "prometheus", mesh42)
        packed = ex.lookup_and_pack(
            [Equals("_metric_", "request_total"), Equals("_ws_", "demo")],
            (START_S + 600) * 1000 - range_ms, QEND_S * 1000,
            by=("_ns_",), fn_name="rate")
        wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                                 STEP_S * 1000)
        return ex.run_agg(packed, wends, range_ms=range_ms,
                          fn_name="rate", agg_op="sum")

    out_gen, labels_gen = run()
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    before = registry.counter("mesh_fused_kernel").value
    err_before = registry.counter("mesh_fused_errors").value
    out_fused, labels_fused = run()
    assert registry.counter("mesh_fused_kernel").value > before, \
        "fused mesh path did not engage"
    assert registry.counter("mesh_fused_errors").value == err_before
    assert labels_fused == labels_gen
    assert (np.isnan(out_fused) == np.isnan(out_gen)).all()
    np.testing.assert_allclose(out_fused, out_gen, rtol=2e-5, atol=1e-4,
                               equal_nan=True)


def test_mesh_fused_skipped_on_ragged_pack(mesh42, monkeypatch):
    """A pack whose shards have different grids must use the general path."""
    from filodb_tpu.utils.metrics import registry
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    ms = TimeSeriesMemStore()
    mapper = ShardMapper(4)
    for s in range(4):
        ms.setup("prometheus", s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", s, "local"))
    # shard 0: full grid; shard 1: offset grid -> pack is not uniform
    ms.get_shard("prometheus", 0).ingest(
        counter_batch(8, NUM_SAMPLES, start_ms=START_MS))
    ms.get_shard("prometheus", 1).ingest(
        counter_batch(8, NUM_SAMPLES // 2, start_ms=START_MS + 5_000,
                      seed=3))
    ex = MeshExecutor(ms, "prometheus", mesh42)
    packed = ex.lookup_and_pack([Equals("_metric_", "request_total")],
                                START_MS, QEND_S * 1000, by=("_ns_",))
    assert packed.shared_ts_row is None
    wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                             STEP_S * 1000)
    before = registry.counter("mesh_fused_kernel").value
    out, _ = ex.run_agg(packed, wends, range_ms=300_000, fn_name="rate",
                        agg_op="sum")
    assert registry.counter("mesh_fused_kernel").value == before
    assert np.isfinite(out).any()


def test_mesh_fused_sum_over_time_matches_general(store4, mesh42,
                                                  monkeypatch):
    """The over_time band-matrix kernel composes on the mesh too."""
    from filodb_tpu.utils.metrics import registry
    ms, mapper = store4
    range_ms = 300_000

    def run():
        ex = MeshExecutor(ms, "prometheus", mesh42)
        packed = ex.lookup_and_pack(
            [Equals("_metric_", "request_total"), Equals("_ws_", "demo")],
            (START_S + 600) * 1000 - range_ms, QEND_S * 1000,
            by=("_ns_",), fn_name="sum_over_time")
        wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                                 STEP_S * 1000)
        return ex.run_agg(packed, wends, range_ms=range_ms,
                          fn_name="sum_over_time", agg_op="sum")

    out_gen, labels_gen = run()
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    before = registry.counter("mesh_fused_kernel").value
    out_fused, labels_fused = run()
    assert registry.counter("mesh_fused_kernel").value > before
    assert labels_fused == labels_gen
    assert (np.isnan(out_fused) == np.isnan(out_gen)).all()
    np.testing.assert_allclose(out_fused, out_gen, rtol=2e-4, atol=1e-3,
                               equal_nan=True)


@pytest.mark.parametrize("agg_op", ["sum", "avg", "count"])
def test_mesh_fused_ragged_pack_matches_general(mesh42, monkeypatch,
                                                agg_op):
    """r4: a uniform-grid pack WITH NaN holes keeps shared_ts_row and runs
    the ragged kernel variant (valid-boundary scans, presence psum'd as a
    second output) — results match the general path's dense=False
    semantics for sum/avg/count."""
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.utils.metrics import registry
    rng = np.random.default_rng(7)
    ms = TimeSeriesMemStore()
    mapper = ShardMapper(4)
    for s in range(4):
        sh = ms.setup("prometheus", s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", s, "local"))
        cb = counter_batch(8, NUM_SAMPLES, start_ms=START_MS, seed=s)
        v = cb.columns["count"].copy()
        v[rng.random(v.shape) < 0.1] = np.nan
        sh.ingest(RecordBatch(cb.schema, cb.part_keys, cb.part_idx,
                              cb.timestamps, {"count": v}, cb.bucket_les))
    ex = MeshExecutor(ms, "prometheus", mesh42)
    packed = ex.lookup_and_pack([Equals("_metric_", "request_total")],
                                START_MS, QEND_S * 1000,
                                fn_name="rate")
    assert packed.shared_ts_row is not None and not packed.dense
    wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                             STEP_S * 1000)
    out_gen, _ = ex.run_agg(packed, wends, range_ms=300_000,
                            fn_name="rate", agg_op=agg_op)
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    before = registry.counter("mesh_fused_kernel").value
    out_fused, _ = ex.run_agg(packed, wends, range_ms=300_000,
                              fn_name="rate", agg_op=agg_op)
    assert registry.counter("mesh_fused_kernel").value > before
    assert (np.isnan(out_fused) == np.isnan(out_gen)).all()
    np.testing.assert_allclose(out_fused, out_gen, rtol=2e-5, atol=1e-4,
                               equal_nan=True)


def test_mesh_fused_avg_divides_by_counts(store4, mesh42, monkeypatch):
    """avg on the fused mesh path must divide group sums by present-series
    counts (r4 regression: it silently returned raw sums)."""
    from filodb_tpu.utils.metrics import registry
    ms, mapper = store4

    def run():
        ex = MeshExecutor(ms, "prometheus", mesh42)
        packed = ex.lookup_and_pack(
            [Equals("_metric_", "request_total"), Equals("_ws_", "demo")],
            (START_S + 600) * 1000 - 300_000, QEND_S * 1000,
            fn_name="rate")
        wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                                 STEP_S * 1000)
        return ex.run_agg(packed, wends, range_ms=300_000,
                          fn_name="rate", agg_op="avg")

    out_gen, _ = run()
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    before = registry.counter("mesh_fused_kernel").value
    out_fused, _ = run()
    assert registry.counter("mesh_fused_kernel").value > before
    np.testing.assert_allclose(out_fused, out_gen, rtol=2e-5, atol=1e-4,
                               equal_nan=True)


def test_run_agg_batch_matches_individual(store4, mesh42, monkeypatch):
    """A dashboard's panels over ONE pack + ONE shard_map dispatch
    (multi-hot over disjoint group-id ranges) must match per-panel
    run_agg exactly; min/max panels fall back per panel."""
    from filodb_tpu.utils.metrics import registry
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    ms, _ = store4
    ex = MeshExecutor(ms, "prometheus", mesh42)
    filters = [Equals("_metric_", "request_total"), Equals("_ws_", "demo")]
    t0 = (START_S + 600) * 1000 - 300_000
    t1 = QEND_S * 1000
    wends = make_window_ends((START_S + 600) * 1000, t1, STEP_S * 1000)
    panels = [(("_ns_",), (), "sum"),
              (("dc",), (), "avg"),
              (("_ns_", "dc"), (), "sum"),
              (("dc",), (), "count"),
              (("_ns_",), (), "max")]     # not fusable: per-panel fallback
    want = []
    for by, wo, op in panels:
        pk = ex.lookup_and_pack(filters, t0, t1, by=by, without=wo,
                                fn_name="rate")
        want.append(ex.run_agg(pk, wends, range_ms=300_000,
                               fn_name="rate", agg_op=op))
    k0 = registry.counter("mesh_fused_kernel").value
    b0 = registry.counter("mesh_fused_batch_panels").value
    got = ex.run_agg_batch(filters, t0, t1, wends, range_ms=300_000,
                           fn_name="rate", panels=panels)
    assert registry.counter("mesh_fused_batch_panels").value - b0 >= 3, \
        "fusable panels did not merge"
    assert registry.counter("mesh_fused_kernel").value - k0 == 1, \
        "merged panels must cost ONE kernel dispatch"
    for (by, wo, op), (w_out, w_labels), (g_out, g_labels) in \
            zip(panels, want, got):
        key = (by, op)
        assert [dict(l) for l in g_labels] == [dict(l) for l in w_labels], key
        assert g_out.shape == w_out.shape, key
        np.testing.assert_allclose(g_out, w_out, rtol=1e-6, atol=1e-9,
                                   equal_nan=True, err_msg=str(key))
    # warm repeat (the dashboard refresh loop): per-panel remaps and the
    # merged gid upload come from _batch_gid_cache; results identical
    again = ex.run_agg_batch(filters, t0, t1, wends, range_ms=300_000,
                             fn_name="rate", panels=panels)
    for (g_out, _), (a_out, _) in zip(got, again):
        np.testing.assert_array_equal(g_out, a_out)
