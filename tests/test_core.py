"""Core memstore tests — models the reference's TimeSeriesMemStoreSpec /
TimeSeriesPartitionSpec / PartKeyLuceneIndexSpec
(ref: core/src/test/.../memstore/)."""
import numpy as np
import pytest

from filodb_tpu.core.index import (Equals, EqualsRegex, In, NotEquals, Prefix,
                                   PartKeyIndex, MAX_TIME)
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey, strip_metric_suffix
from filodb_tpu.core.records import RecordBatch, RecordBatchBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, GAUGE, PROM_COUNTER
from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.ingest.generator import (gauge_batch, counter_batch,
                                         histogram_batch, batch_stream)


# ---------------------------------------------------------------- part keys

def test_partkey_identity_and_hashes():
    pk1 = PartKey.make("heap_usage", {"_ws_": "demo", "_ns_": "App-0", "instance": "i1"})
    pk2 = PartKey.make("heap_usage", {"instance": "i1", "_ns_": "App-0", "_ws_": "demo"})
    assert pk1 == pk2
    assert pk1.to_bytes() == pk2.to_bytes()
    assert pk1.partition_hash() == pk2.partition_hash()
    pk3 = PartKey.make("heap_usage", {"_ws_": "demo", "_ns_": "App-1", "instance": "i1"})
    assert pk1.partition_hash() != pk3.partition_hash()


def test_partkey_le_excluded_from_hash():
    # `le` is excluded from the partition hash (ignoreTagsOnPartitionKeyHash)
    a = PartKey.make("lat_bucket", {"_ws_": "w", "_ns_": "n", "le": "0.5"})
    b = PartKey.make("lat_bucket", {"_ws_": "w", "_ns_": "n", "le": "2.5"})
    assert a.partition_hash() == b.partition_hash()
    assert a.to_bytes() != b.to_bytes()


def test_shard_key_suffix_stripping():
    # _bucket/_count/_sum share the base metric's shard key
    assert strip_metric_suffix("http_latency_bucket") == "http_latency"
    a = PartKey.make("http_latency_bucket", {"_ws_": "w", "_ns_": "n"})
    b = PartKey.make("http_latency_sum", {"_ws_": "w", "_ns_": "n"})
    c = PartKey.make("http_latency", {"_ws_": "w", "_ns_": "n"})
    assert a.shard_key_hash() == b.shard_key_hash() == c.shard_key_hash()


def test_copy_tags_derives_ns():
    pk = PartKey.make("m", {"_ws_": "w", "job": "scraper"})
    assert pk.label("_ns_") == "scraper"


# ---------------------------------------------------------------- schemas

def test_default_schemas():
    s = DEFAULT_SCHEMAS
    assert set(s.by_name) == {"gauge", "untyped", "prom-counter",
                              "prom-histogram", "ds-gauge"}
    assert s["prom-counter"].column("count").detect_drops
    assert s["prom-histogram"].column("h").col_type == "hist"
    assert s["gauge"].downsample_schema == "ds-gauge"
    # ids stable and distinct
    assert len({sch.schema_id for sch in s.by_name.values()}) == 5


# ---------------------------------------------------------------- tag index

def _mk_index():
    idx = PartKeyIndex()
    for i in range(10):
        pk = PartKey.make("heap_usage", {"_ws_": "demo", "_ns_": f"App-{i % 3}",
                                         "instance": f"Instance-{i}"})
        idx.add_partition(i, pk, start_time_ms=1000 * i)
    return idx


def test_index_equals_and_in():
    idx = _mk_index()
    ids = idx.part_ids_from_filters([Equals("_ns_", "App-0")], 0, MAX_TIME)
    assert sorted(ids.tolist()) == [0, 3, 6, 9]
    ids = idx.part_ids_from_filters(
        [In("_ns_", ("App-0", "App-1")), Equals("__name__", "heap_usage")],
        0, MAX_TIME)
    assert sorted(ids.tolist()) == [0, 1, 3, 4, 6, 7, 9]


def test_index_regex_prefix_notequals():
    idx = _mk_index()
    ids = idx.part_ids_from_filters([EqualsRegex("instance", "Instance-[12]")],
                                    0, MAX_TIME)
    assert sorted(ids.tolist()) == [1, 2]
    ids = idx.part_ids_from_filters([Prefix("instance", "Instance-1")], 0, MAX_TIME)
    assert sorted(ids.tolist()) == [1]
    ids = idx.part_ids_from_filters([NotEquals("_ns_", "App-0")], 0, MAX_TIME)
    assert sorted(ids.tolist()) == [1, 2, 4, 5, 7, 8]


def test_index_time_range_and_end_time():
    idx = _mk_index()
    idx.update_end_time(0, 1500)
    ids = idx.part_ids_from_filters([Equals("_ns_", "App-0")], 2000, MAX_TIME)
    assert 0 not in ids.tolist()
    # start-time filter: series starting after query end excluded
    ids = idx.part_ids_from_filters([], 0, 4500)
    assert sorted(ids.tolist()) == [0, 1, 2, 3, 4]


def test_index_label_values_and_names():
    idx = _mk_index()
    assert idx.label_values("_ns_") == ["App-0", "App-1", "App-2"]
    assert idx.label_values("_ns_", [Equals("instance", "Instance-4")]) == ["App-1"]
    assert "instance" in idx.label_names()
    assert idx.label_values("__name__") == ["heap_usage"]


def test_index_remove_partition():
    idx = _mk_index()
    idx.remove_partition(0)
    ids = idx.part_ids_from_filters([Equals("_ns_", "App-0")], 0, MAX_TIME)
    assert 0 not in ids.tolist()
    assert idx.num_docs == 9


# ---------------------------------------------------------------- records

def test_record_batch_roundtrip():
    batch = gauge_batch(5, 10)
    blob = batch.to_bytes()
    out = RecordBatch.from_bytes(blob)
    assert out.schema.name == "gauge"
    assert out.part_keys == batch.part_keys
    np.testing.assert_array_equal(out.timestamps, batch.timestamps)
    np.testing.assert_array_equal(out.columns["value"], batch.columns["value"])


def test_record_batch_hist_roundtrip():
    batch = histogram_batch(3, 5, num_buckets=4)
    out = RecordBatch.from_bytes(batch.to_bytes())
    assert out.columns["h"].shape == (15, 4)
    np.testing.assert_array_equal(out.columns["h"], batch.columns["h"])
    np.testing.assert_array_equal(out.bucket_les, batch.bucket_les)


def test_record_builder():
    b = RecordBatchBuilder(GAUGE)
    pk = PartKey.make("m", {"_ws_": "w", "_ns_": "n"})
    for i in range(5):
        b.add(pk, 1000 + i * 10, value=float(i))
    batch = b.build()
    assert batch.num_records == 5
    assert len(batch.part_keys) == 1  # interned


# ---------------------------------------------------------------- memstore

def test_shard_ingest_and_lookup():
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    batch = gauge_batch(20, 50)
    n = shard.ingest(batch, offset=1)
    assert n == 1000
    assert shard.num_partitions == 20
    res = shard.lookup_partitions([Equals("_ns_", "App-0")], 0, MAX_TIME)
    assert len(res.parts_by_schema["gauge"]) == 2
    ts, cols, counts, store = shard.gather_series(res.parts_by_schema["gauge"])
    assert ts.shape[0] == 2
    assert (counts == 50).all()
    # values are finite where counts valid
    assert np.isfinite(cols["value"][0, :50]).all()


def test_shard_out_of_order_dropped():
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    b1 = gauge_batch(2, 10, start_ms=1_000_000)
    shard.ingest(b1)
    # replay the same data: all out-of-order, all dropped
    n = shard.ingest(gauge_batch(2, 10, start_ms=1_000_000))
    assert n == 0
    assert shard.stats.rows_dropped == 20


def test_flush_and_recovery_roundtrip():
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard = ms.setup("prometheus", 0)
    batch = gauge_batch(10, 40)
    stream = list(batch_stream(batch, samples_per_chunk=10))
    for b, off in stream:
        shard.ingest(b, off)
    shard.flush_all_groups()
    assert cs.num_chunksets() == 10  # one sealed chunk per series for this flush
    # checkpoints recorded for all groups
    cps = meta.read_checkpoints("prometheus", 0)
    assert len(cps) == shard._groups
    assert meta.read_highest_checkpoint("prometheus", 0) == 3

    # new node: recover index from column store, then replay stream
    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard2 = ms2.setup("prometheus", 0)
    assert shard2.recover_index() == 10
    assert shard2.num_partitions == 10
    replayed = shard2.recover_stream(stream)
    # all offsets <= checkpoint watermark are skipped
    assert replayed == 0


def test_recovery_partial_checkpoint():
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard = ms.setup("prometheus", 0)
    batch = gauge_batch(4, 40)
    stream = list(batch_stream(batch, samples_per_chunk=10))
    # ingest only first 2 offsets, flush, then "crash"
    for b, off in stream[:2]:
        shard.ingest(b, off)
    shard.flush_all_groups()

    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard2 = ms2.setup("prometheus", 0)
    shard2.recover_index()
    replayed = shard2.recover_stream(stream)
    # offsets 2,3 replayed (2 batches x 4 series x 10 samples)
    assert replayed == 2 * 4 * 10


def test_eviction():
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    shard.ingest(gauge_batch(5, 10, start_ms=1_000_000))
    for pid in range(5):
        shard.index.update_end_time(pid, 1_050_000)
    n = shard.evict_ended_partitions(2_000_000)
    assert n == 5
    assert shard.num_partitions == 0


def test_dense_store_time_growth_and_eviction():
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    for i in range(4):
        shard.ingest(gauge_batch(3, 100, start_ms=1_000_000 + i * 100 * 10_000))
    store = shard.stores["gauge"]
    assert (store.counts[:3] == 400).all()
    # unflushed samples are never evicted (reclaim-only-persisted guarantee)
    store.evict_oldest(100)
    assert (store.counts[:3] == 400).all()
    shard.flush_all_groups()
    store.evict_oldest(100)
    assert (store.counts[:3] == 300).all()
    ts, cols, counts = store.gather_rows(np.array([0, 1, 2]))
    assert np.isfinite(cols["value"][:, :300]).all()


def test_partkey_bytes_no_delimiter_collision():
    # label values may contain any byte; length-prefixed encoding must keep
    # distinct series distinct (regression: \x00/\x01-joined encoding collided)
    a = PartKey.make("m", {"a": "b\x01c\x00d"})
    b = PartKey.make("m", {"a": "b", "c": "d"})
    assert a.to_bytes() != b.to_bytes()
    assert a.partition_hash() != b.partition_hash()
    assert PartKey.from_bytes(a.to_bytes()) == a
    assert PartKey.from_bytes(b.to_bytes()) == b


def test_recordbatch_roundtrip_hostile_labels():
    from filodb_tpu.core.records import RecordBatchBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    bld = RecordBatchBuilder(DEFAULT_SCHEMAS["gauge"])
    pk = PartKey.make("m\x02x", {"k\x01": "v\x00\x02w"})
    bld.add(pk, 1_000, value=1.5)
    batch = bld.build()
    rt = RecordBatch.from_bytes(batch.to_bytes())
    assert rt.part_keys == [pk]
    assert rt.timestamps.tolist() == [1_000]


def test_flush_group_stable_across_restart_no_data_loss():
    """Crash-replay scenario: group checkpoints must filter by a partKey-stable
    group id, or unflushed records get silently dropped on recovery."""
    cs, mstore = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=mstore)
    shard = ms.setup("prometheus", 0)
    batch = gauge_batch(8, 50, start_ms=1_000_000)
    shard.ingest(batch, offset=10)
    # flush only ONE group, then "crash" (other groups unflushed)
    flushed_group = shard.partitions[0].group
    shard.flush_group(flushed_group)

    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=mstore)
    shard2 = ms2.setup("prometheus", 0)
    shard2.recover_index()
    replayed = shard2.recover_stream([(batch, 10)])
    # every record NOT in the flushed group must be replayed
    expect = sum(50 for p in shard.partitions
                 if p is not None and p.group != flushed_group)
    assert replayed == expect
    # and total samples visible after recovery covers all 8 series
    for p in shard2.partitions:
        store = shard2.stores[p.schema_name]
        if p.group == flushed_group:
            # flushed data lives in the column store (ODP tier), not memstore
            continue
        assert store.counts[p.row] == 50


def test_evict_preserves_unsealed_low_volume_series():
    """One hot series overflowing must not destroy another series' unflushed
    samples (regression: uniform-shift eviction)."""
    from filodb_tpu.core.blockstore import DenseSeriesStore
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    store = DenseSeriesStore(DEFAULT_SCHEMAS["gauge"], initial_series=2,
                             initial_time=8, max_time_cap=64)
    hot, cold = store.new_row(), store.new_row()
    # cold series: 5 unflushed samples
    store.append_batch(np.full(5, cold), np.arange(5, dtype=np.int64) * 1000 + 1,
                       {"value": np.arange(5, dtype=float)})
    # hot series: flood past max_time_cap
    n = 100
    store.append_batch(np.full(n, hot), np.arange(n, dtype=np.int64) * 1000 + 1,
                       {"value": np.ones(n)})
    assert store.counts[cold] == 5
    vals = store.cols["value"][cold, :5]
    np.testing.assert_array_equal(vals, np.arange(5, dtype=float))


def test_windowed_gather_bounds_after_evict_and_prepend():
    """Round-5 windowed gather: the per-position timestamp bounds must
    stay CONSERVATIVE (never exclude in-window data) across the two
    position-rearranging mutations — eviction left-shifts and ODP
    prepend right-shifts."""
    import numpy as np
    from filodb_tpu.core.blockstore import DenseSeriesStore
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS

    schema = DEFAULT_SCHEMAS["gauge"]
    st = DenseSeriesStore(schema, initial_series=4, initial_time=16,
                          max_time_cap=64)
    rows = np.array([st.new_row() for _ in range(3)])

    def append(t0, n):
        ts = np.repeat(np.arange(t0, t0 + n) * 1000, 1)
        for r in rows:
            st.append_batch(np.full(n, r), np.arange(t0, t0 + n) * 1000,
                            {"value": np.arange(t0, t0 + n, dtype=float)})

    append(10, 40)                         # ts 10_000..49_000

    def gathered_ts(t_lo, t_hi):
        ts, cols, counts = st.gather_rows(rows, t_lo, t_hi)
        out = []
        for i in range(len(rows)):
            row = ts[i][:counts[i]]
            out.append(row[(row >= t_lo) & (row <= t_hi)])
        return out

    # full in-window coverage before any shift
    want = np.arange(20, 30) * 1000
    for row in gathered_ts(20_000, 29_000):
        np.testing.assert_array_equal(row, want)

    # eviction shifts rows left; bounds must be recomputed
    st.mark_sealed(int(rows[0]), 30)
    st.mark_sealed(int(rows[1]), 30)
    st.mark_sealed(int(rows[2]), 30)
    st.evict_oldest(12)
    for row in gathered_ts(30_000, 45_000):
        np.testing.assert_array_equal(row, np.arange(30, 46) * 1000)

    # ODP prepend shifts one row right; its bounds updates are row-wise
    pre_ts = np.arange(2, 10) * 1000       # data older than the oldest
    st.prepend_row(int(rows[0]), pre_ts,
                   {"value": pre_ts.astype(float)})
    got = gathered_ts(2_000, 9_000)
    np.testing.assert_array_equal(got[0], pre_ts)
    # windows covering everything still return everything
    for i, row in enumerate(gathered_ts(22_000, 49_000)):
        np.testing.assert_array_equal(row, np.arange(22, 50) * 1000)


def test_windowed_gather_counts_relative():
    """gather_rows with bounds returns slice-relative counts and a
    non-empty matrix even for windows entirely outside the data."""
    import numpy as np
    from filodb_tpu.core.blockstore import DenseSeriesStore
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS

    st = DenseSeriesStore(DEFAULT_SCHEMAS["gauge"],
                          initial_series=2, initial_time=8)
    r = st.new_row()
    st.append_batch(np.zeros(6, np.int64), np.arange(6) * 1000,
                    {"value": np.arange(6, dtype=float)})
    ts, cols, counts = st.gather_rows(np.array([r]), 2_000, 4_000)
    assert ts.shape[1] >= 1 and counts[0] >= 3
    # fully out-of-range window: 1 pad-masked column, zero count is fine
    ts2, _, counts2 = st.gather_rows(np.array([r]), 99_000, 100_000)
    assert ts2.shape[1] >= 1


def test_window_positions_bounds_invariant_fuzz():
    """Property fuzz: after ANY interleaving of appends, evictions, and
    prepends, window_positions(lo, hi) must cover every live cell with
    lo <= ts <= hi in every row (bounds may be wider, never narrower)."""
    import numpy as np
    from filodb_tpu.core.blockstore import DenseSeriesStore
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS

    rng = np.random.default_rng(42)
    st = DenseSeriesStore(DEFAULT_SCHEMAS["gauge"], initial_series=4,
                          initial_time=8, max_time_cap=96)
    rows = np.array([st.new_row() for _ in range(3)])
    next_ts = {int(r): 100 + 10 * int(r) for r in rows}
    oldest = {int(r): next_ts[int(r)] for r in rows}

    def check():
        for lo, hi in [(0, 10**9), (500, 900), (1, 400), (700, 701)]:
            p_lo, p_hi = st.window_positions(lo, hi)
            for r in rows:
                c = int(st.counts[r])
                ts_r = st.ts[r, :c]
                inside = np.flatnonzero((ts_r >= lo) & (ts_r <= hi))
                if inside.size:
                    assert p_lo <= inside.min() and inside.max() < p_hi, (
                        lo, hi, p_lo, p_hi, inside.min(), inside.max())

    for step in range(120):
        op = rng.integers(0, 10)
        if op < 6:                                   # append burst
            n = int(rng.integers(1, 4))
            for r in rows:
                t0 = next_ts[int(r)]
                ts = np.arange(t0, t0 + n) * 1  # ms-scale ints
                st.append_batch(np.full(n, r), ts,
                                {"value": ts.astype(float)})
                next_ts[int(r)] = t0 + n
        elif op < 8:                                 # seal + evict
            for r in rows:
                st.mark_sealed(int(r), int(st.counts[r]) // 2)
            st.evict_oldest(int(rng.integers(1, 5)))
            for r in rows:
                c = int(st.counts[r])
                if c:
                    oldest[int(r)] = int(st.ts[r, 0])
        else:                                        # ODP prepend one row
            r = int(rows[rng.integers(0, len(rows))])
            c = int(st.counts[r])
            first = int(st.ts[r, 0]) if c else next_ts[r]
            m = int(rng.integers(1, 3))
            pre = np.arange(first - m, first)
            if pre[0] > 0:
                st.prepend_row(r, pre, {"value": pre.astype(float)})
        check()


def test_lookup_partitions_cache_invalidation():
    """The lookup_partitions memo (round 5: dashboards repeat one selector
    per panel) must return cached results for repeat lookups, see NEW
    series the moment the index mutates, and drop evicted ones."""
    from filodb_tpu.ingest.generator import gauge_batch
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    shard.ingest(gauge_batch(20, 50), offset=1)
    filt = [Equals("_ns_", "App-0")]
    r1 = shard.lookup_partitions(filt, 0, MAX_TIME)
    r2 = shard.lookup_partitions(filt, 0, MAX_TIME)
    assert r2 is r1                       # memo hit: same object
    # equal-but-distinct filter objects hit too (frozen dataclass hash)
    r3 = shard.lookup_partitions([Equals("_ns_", "App-0")], 0, MAX_TIME)
    assert r3 is r1
    # different range misses
    r4 = shard.lookup_partitions(filt, 0, 10)
    assert r4 is not r1
    # ingesting a NEW matching series invalidates: the next lookup sees it
    before = r1.part_ids.size
    extra = gauge_batch(40, 10, start_ms=1_600_000_000_000 + 50 * 10_000)
    shard.ingest(extra, offset=2)
    r5 = shard.lookup_partitions(filt, 0, MAX_TIME)
    assert r5 is not r1
    assert r5.part_ids.size > before


def test_index_absent_label_empty_string_convention():
    """PromQL: a series without label L has L="" for matching (round-5
    fix) — Equals/In/EqualsRegex and their negations must treat absent
    and empty-matching consistently at the INDEX level."""
    from filodb_tpu.core.index import NotEqualsRegex, NotIn
    idx = PartKeyIndex()
    idx.add_partition(0, PartKey.make("m", {"job": "api", "env": "prod"}), 0)
    idx.add_partition(1, PartKey.make("m", {"job": "app"}), 0)
    T = 1 << 62

    def ids(f):
        return sorted(idx.part_ids_from_filters([f], 0, T).tolist())

    assert ids(Equals("env", "")) == [1]
    assert ids(NotEquals("env", "")) == [0]
    assert ids(Equals("env", "prod")) == [0]
    assert ids(NotEquals("env", "prod")) == [1]
    assert ids(In("env", ("prod", ""))) == [0, 1]
    assert ids(NotIn("env", ("prod", ""))) == []
    assert ids(EqualsRegex("env", "prod|")) == [0, 1]
    assert ids(EqualsRegex("env", ".+")) == [0]
    assert ids(NotEqualsRegex("env", ".+")) == [1]
    assert ids(NotEqualsRegex("env", "prod|")) == []
    assert ids(EqualsRegex("env", "")) == [1]
