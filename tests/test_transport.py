"""Wire serialization + cross-node dispatch tests (models ref:
coordinator/src/test/.../client/SerializationSpec — the Kryo regression net —
and the multi-JVM cluster query specs)."""
import numpy as np
import pytest

from filodb_tpu.core.index import Equals, EqualsRegex
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.gateway.router import split_batch_by_shard
from filodb_tpu.ingest.generator import counter_batch, gauge_batch
from filodb_tpu.parallel import serialize
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             SpreadProvider)
from filodb_tpu.parallel.transport import NodeQueryServer, RemoteNodeDispatcher
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.exec import (AggPartial, AggregateMapReduce,
                                   AggregatePresenter, DistConcatExec,
                                   MultiSchemaPartitionsExec,
                                   PeriodicSamplesMapper)
from filodb_tpu.query.planner import SingleClusterPlanner
from filodb_tpu.query.rangevector import (QueryContext, RangeVectorKey,
                                          ResultBlock)

START = 1_600_000_020_000
S = START // 1000


# ----------------------------------------------------------- serialization


def test_result_block_roundtrip():
    keys = [RangeVectorKey.make({"job": "a", "inst": "1"}),
            RangeVectorKey.make({"job": "b"})]
    wends = np.arange(5, dtype=np.int64) * 1000
    vals = np.random.default_rng(0).normal(size=(2, 5))
    vals[0, 2] = np.nan
    b = ResultBlock(keys, wends, vals)
    b2 = serialize.loads(serialize.dumps(b))
    assert b2.keys == keys
    np.testing.assert_array_equal(b2.wends, wends)
    np.testing.assert_array_equal(b2.values, vals)
    # decoded arrays must be writable (consumers mutate)
    b2.values[0, 0] = 42.0


def test_agg_partial_roundtrip_both_forms():
    keys = [RangeVectorKey.make({"g": "x"})]
    wends = np.asarray([1000, 2000], dtype=np.int64)
    comp = np.ones((1, 2, 2))
    p = AggPartial("avg", keys, wends, comp=comp)
    p2 = serialize.loads(serialize.dumps(p))
    assert p2.op == "avg" and p2.group_keys == keys
    np.testing.assert_array_equal(p2.comp, comp)

    cand = AggPartial("topk", keys, wends, cand_keys=keys,
                      cand_vals=np.ones((1, 2)),
                      cand_groups=np.zeros(1, dtype=np.int64),
                      params=(3.0,))
    c2 = serialize.loads(serialize.dumps(cand))
    assert c2.params == (3.0,)
    np.testing.assert_array_equal(c2.cand_vals, cand.cand_vals)


def test_leaf_plan_roundtrip_preserves_tree_and_result():
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(counter_batch(8, 360, start_ms=START))
    ctx = QueryContext(query_id="q1")
    plan = MultiSchemaPartitionsExec(
        ctx, "prometheus", 0,
        [Equals("_metric_", "request_total"), EqualsRegex("_ns_", "App.*")],
        START, START + 3_600_000)
    plan.add_transformer(PeriodicSamplesMapper(
        START + 600_000, 60_000, START + 3_600_000, 300_000, "rate", ()))
    plan.add_transformer(AggregateMapReduce("sum", (), (), ()))
    plan2 = serialize.loads(serialize.dumps(plan))
    assert plan2.print_tree() == plan.print_tree()
    d1, _ = plan.execute_internal(ms)
    d2, _ = plan2.execute_internal(ms)
    np.testing.assert_array_equal(np.asarray(d1.comp), np.asarray(d2.comp))


def test_nonleaf_plans_refuse_serialization():
    ctx = QueryContext()
    with pytest.raises(serialize.NotSerializable):
        serialize.dumps(DistConcatExec(ctx, []))


def test_presenter_roundtrip():
    p = AggregatePresenter("quantile", (0.9,))
    p2 = serialize.loads(serialize.dumps(p))
    assert p2.op == "quantile" and p2.params == (0.9,)


# ------------------------------------------------------- cross-node cluster


@pytest.fixture(scope="module")
def cluster():
    """Two node processes (in-process servers), 4 shards, coordinator with
    remote dispatchers — the multi-JVM IngestionAndRecoverySpec shape.
    Wiring shared with the dispatch benchmark (parallel/testcluster.py)."""
    from filodb_tpu.parallel.testcluster import make_two_node_cluster
    c = make_two_node_cluster(
        [counter_batch(40, 360, start_ms=START),
         gauge_batch(30, 360, start_ms=START)], with_truth=True)
    truth_eng = QueryEngine("prometheus", c.truth, c.mapper,
                            SpreadProvider(default_spread=1))
    yield c.engine, truth_eng
    c.stop()


@pytest.mark.parametrize("q", [
    'sum(rate(request_total[5m]))',
    'sum by (_ns_)(rate(request_total[5m]))',
    'avg(heap_usage{_ws_="demo"})',
    'topk(3,heap_usage)',
    'quantile(0.9,rate(request_total[5m]))',
])
def test_distributed_query_matches_local(cluster, q):
    eng, truth_eng = cluster
    r1 = eng.query_range(q, S + 600, 60, S + 3600)
    r2 = truth_eng.query_range(q, S + 600, 60, S + 3600)
    assert r1.error is None, r1.error
    assert r2.error is None
    m1 = {k: v for k, _, v in r1.series()}
    m2 = {k: v for k, _, v in r2.series()}
    assert set(m1) == set(m2)
    for k in m1:
        np.testing.assert_allclose(m1[k], m2[k], rtol=1e-9, equal_nan=True)


def test_distributed_metadata_queries(cluster):
    eng, truth_eng = cluster
    from filodb_tpu.query import logical as lp
    plan = lp.LabelValues(("_ns_",), (), 0, 1 << 62)
    r1 = eng.exec_logical_plan(plan)
    r2 = truth_eng.exec_logical_plan(plan)
    assert r1.error is None
    assert sorted(r1.data["_ns_"]) == sorted(r2.data["_ns_"])


def test_missing_dataset_returns_empty(cluster):
    eng, _ = cluster
    leaf = MultiSchemaPartitionsExec(QueryContext(), "nope", 0, [], 0, 10)
    leaf.dispatcher = eng.planner._dispatcher(0) or leaf.dispatcher
    data, stats = leaf.dispatcher.dispatch(leaf, None)
    assert data is None


def test_remote_exception_rides_wire_as_error():
    """A server-side crash must come back as ok=False and surface as a
    typed QueryError(remote_failure) naming the node (ref: QueryActor
    error replies; taxonomy in doc/query-engine.md)."""
    from filodb_tpu.query.execbase import QueryError

    class _ExplodingSource:
        def get_shard(self, dataset, shard_num):
            raise RuntimeError("store corrupted")

    srv = NodeQueryServer(_ExplodingSource()).start()
    try:
        disp = RemoteNodeDispatcher(*srv.address)
        leaf = MultiSchemaPartitionsExec(QueryContext(), "prometheus", 0,
                                         [], 0, 10)
        with pytest.raises(QueryError) as ei:
            disp.dispatch(leaf, None)
        assert ei.value.code == "remote_failure"
        assert "store corrupted" in str(ei.value)
        assert str(srv.address[1]) in str(ei.value)
    finally:
        srv.stop()


def test_bench_dispatch_smoke():
    """The cross-node dispatch bench workload runs and emits a JSON line."""
    import io
    import json
    from contextlib import redirect_stdout

    from bench.suite import bench_dispatch
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench_dispatch(quick=True)
    line = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["bench"] == "dispatch" and line["value"] > 0


def test_partial_results_keep_join_child_positions():
    """allow_partial_results must not SHIFT surviving children into the
    wrong side of a positional split: BinaryJoinExec splits gathered
    results at n_lhs, so a dropped lhs child needs a placeholder, never
    compaction (silently joining an rhs block as an lhs operand)."""
    import numpy as np

    from filodb_tpu.query.execbase import (ExecPlan, QueryError)
    from filodb_tpu.query.nonleaf import BinaryJoinExec
    from filodb_tpu.query.rangevector import (PlannerParams, QueryContext,
                                              QueryStats, RangeVectorKey,
                                              ResultBlock)

    wends = np.array([1000, 2000], np.int64)

    class _Static(ExecPlan):
        def __init__(self, ctx, label, value):
            super().__init__(ctx)
            self._block = ResultBlock(
                [RangeVectorKey((("inst", label),))], wends,
                np.full((1, 2), value))

        def _do_execute(self, source):
            return self._block, QueryStats()

    class _Dead(ExecPlan):
        def _do_execute(self, source):
            raise QueryError("shard_unavailable", "owner SIGKILLed")

    # partial_now is what the ENGINE sets once re-plan retries are
    # exhausted (PR 4 retry-then-degrade); at the _gather level it is
    # the switch that actually authorizes dropping a dead child
    ctx = QueryContext(
        planner_params=PlannerParams(allow_partial_results=True,
                                     partial_now=True))
    dead = _Dead(ctx)
    lhs_ok = _Static(ctx, "a", 10.0)
    rhs_a = _Static(ctx, "a", 1.0)
    rhs_b = _Static(ctx, "b", 2.0)
    join = BinaryJoinExec(ctx, [dead, lhs_ok], [rhs_a, rhs_b], "+")
    res = join.execute(None)
    assert res.error is None
    assert res.partial is True
    series = {k.labels_dict["inst"]: v for k, _, v in res.series()}
    # the surviving lhs child (inst=a, 10.0) joins rhs inst=a (1.0);
    # without the placeholder, rhs_a would have been consumed as an LHS
    # operand and the sums would be wrong/misassigned
    assert set(series) == {"a"}
    np.testing.assert_allclose(series["a"], [11.0, 11.0])

    # without the opt-in the same death fails the query with the code
    ctx2 = QueryContext(planner_params=PlannerParams())
    join2 = BinaryJoinExec(ctx2, [_Dead(ctx2), _Static(ctx2, "a", 10.0)],
                           [_Static(ctx2, "a", 1.0)], "+")
    res2 = join2.execute(None)
    assert res2.error is not None
    assert res2.error.startswith("shard_unavailable")
