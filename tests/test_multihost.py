"""Multi-host runtime helpers (single-process equivalence; real multi-host
needs a pod — the contract is that one process degrades exactly to the
local mesh path, ref: SURVEY §2.9 comm backend)."""
import numpy as np
import pytest

import jax

from filodb_tpu.parallel import multihost
from filodb_tpu.parallel.mesh import (MeshExecutor, device_put_packed,
                                      make_mesh, pack_shards)


def test_initialize_single_process_is_noop():
    multihost.initialize(num_processes=1)     # must not raise or connect


def test_global_mesh_shapes():
    mesh = multihost.global_mesh(n_shard=4, n_time=2)
    assert mesh.shape == {"shard": 4, "time": 2}
    with pytest.raises(ValueError):
        multihost.global_mesh(n_shard=64, n_time=64)


def test_multihost_put_matches_local_put():
    """Under one process device_put_packed_multihost must produce arrays
    identical to the local path — same shardings, same values."""
    rng = np.random.default_rng(0)
    blocks = []
    for d in range(4):
        ts = np.arange(12, dtype=np.int32)[None, :].repeat(3, 0)
        vals = rng.normal(size=(3, 12))
        labels = [{"_ns_": f"App-{i % 2}", "inst": f"d{d}-{i}"}
                  for i in range(3)]
        blocks.append((ts, vals, labels))
    packed = pack_shards(blocks, by=("_ns_",), base_ms=0)
    mesh = multihost.global_mesh(n_shard=4, n_time=2)
    a = device_put_packed(packed, mesh)
    b = multihost.device_put_packed_multihost(packed, mesh)
    for name in ("ts_off", "values", "group_ids"):
        x, y = getattr(a, name), getattr(b, name)
        assert x.sharding == y.sharding, name
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_multihost_mesh_runs_spmd_agg():
    """The global-mesh arrays drive the same SPMD program end to end."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.core.index import Equals
    from filodb_tpu.ops.timewindow import make_window_ends
    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0)
    ms.setup("prometheus", 1)
    b = counter_batch(8, 120, start_ms=START)
    ms.ingest("prometheus", 0, b, offset=1)
    mesh = multihost.global_mesh(n_shard=2, n_time=2)
    ex = MeshExecutor(ms, "prometheus", mesh)
    end = START + 119 * 10_000
    p = ex.lookup_and_pack([Equals("_metric_", "request_total")], START, end,
                           by=("_ns_",), fn_name="rate")
    wends = make_window_ends(START + 400_000, end, 60_000)
    out, labels = ex.run_agg(p, wends, range_ms=300_000, fn_name="rate",
                             agg_op="sum")
    assert np.isfinite(np.asarray(out)).any()
    assert len(labels) >= 1
