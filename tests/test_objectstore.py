"""Disaggregated cold tier (persist/objectstore.py): content-addressed
segment objects + dedup, manifest atomic swap + torn-write recovery,
upload retry/backoff through the objectstore.* fault points, the
prune-blocked-on-upload durability gate, disk-kill rebuild
bit-identity, stateless query-only nodes, and the dead-store ->
flagged-partial degrade."""
import os
import shutil

import numpy as np
import pytest

from filodb_tpu.core.devicecache import ColdSegmentCache
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.parallel.breaker import breakers
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             SpreadProvider)
from filodb_tpu.persist.compactor import SegmentCompactor
from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                           LocalDiskMetaStore)
from filodb_tpu.persist.objectstore import (LocalObjectStore,
                                            ObjectStoreCorruption,
                                            ObjectStoreUnavailable,
                                            RemoteSegmentStore,
                                            SegmentUploader, content_key,
                                            restore_from_objectstore)
from filodb_tpu.persist.segments import PersistedTier, SegmentStore
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planners import PersistedClusterPlanner
from filodb_tpu.utils.events import journal
from filodb_tpu.utils.faults import faults

DS = "obj-test"
WINDOW = 3600 * 1000
T0 = 1_600_000_000_000 - (1_600_000_000_000 % WINDOW)
INTERVAL = 60_000
N_WINDOWS = 2
NS = N_WINDOWS * WINDOW // INTERVAL
S = 4


@pytest.fixture(autouse=True)
def _fresh_failure_state():
    """Faults + breakers are process-global; every test starts closed and
    disarmed (a breaker left open by one test must not fail-fast the
    next)."""
    faults.disarm()
    breakers.configure(failure_threshold=1000, open_base_s=0.01,
                       open_max_s=0.05, jitter=0.0)
    breakers.reset()
    yield
    faults.disarm()
    breakers.configure()
    breakers.reset()


def _pks():
    return [PartKey("m", (("inst", f"i{i}"), ("_ws_", "w"), ("_ns_", "n")))
            for i in range(S)]


def _grid():
    return T0 + np.arange(NS, dtype=np.int64) * INTERVAL


def _vals():
    # small integers: exact in f32, so restored/remote reads must agree
    # BIT-identically with the pre-kill baseline
    return (np.arange(S)[:, None] * 50.0 + (np.arange(NS) % 11)[None, :])


def _disk_setup(tmp_path):
    """Disk-backed store with two closed windows flushed."""
    cs = LocalDiskColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(column_store=cs,
                            meta_store=LocalDiskMetaStore(str(tmp_path)))
    shard = ms.setup(DS, 0)
    ts_grid, vals = _grid(), _vals()
    shard.ingest_columns("gauge", _pks(),
                         np.broadcast_to(ts_grid, (S, NS)),
                         {"value": vals})
    shard.flush_all_groups()
    return cs, ms, shard, ts_grid, vals


def _compacted(tmp_path):
    cs, ms, shard, ts_grid, vals = _disk_setup(tmp_path)
    seg_store = SegmentStore(str(tmp_path))
    comp = SegmentCompactor(cs, seg_store, DS, 1, window_ms=WINDOW,
                            closed_lag_ms=0)
    now = int(ts_grid[-1]) + 10 * WINDOW
    assert comp.compact_all(now_ms=now) == N_WINDOWS
    return cs, seg_store, comp, ts_grid, vals, now


def _obj_store(tmp_path, name="shared"):
    return LocalObjectStore(str(tmp_path / "objstore"), name=name)


# ----------------------------------------------------- content addressing


def test_content_address_roundtrip_and_dedup(tmp_path):
    store = _obj_store(tmp_path)
    key, wrote = store.put_object(b"segment payload")
    assert wrote and key == content_key(b"segment payload")
    # second put of identical bytes is a dedup hit, not a rewrite
    key2, wrote2 = store.put_object(b"segment payload")
    assert key2 == key and not wrote2
    assert store.get_object(key) == b"segment payload"
    assert store.list("objects") == [key]

    # flip one byte in the stored object: the content hash IS the key,
    # so the corruption is detected and never served as data
    path = store._path(key)
    raw = bytearray(open(path, "rb").read())
    raw[0] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ObjectStoreCorruption):
        store.get_object(key)


def test_get_missing_key_is_keyerror_not_store_death(tmp_path):
    store = _obj_store(tmp_path)
    with pytest.raises(KeyError):
        store.get("objects/ab/absent")
    assert store.breaker.state == "closed"


# ----------------------------------------------------- manifest swapping


def _upload_all(tmp_path, store=None):
    cs, seg_store, comp, ts_grid, vals, now = _compacted(tmp_path)
    store = store or _obj_store(tmp_path)
    up = SegmentUploader(store, seg_store, DS, 1, retry_base_s=0.001,
                         retry_max_s=0.01)
    up.mount()
    return cs, seg_store, comp, store, up, ts_grid, vals, now


def test_manifest_atomic_swap_and_torn_write_recovery(tmp_path):
    cs, seg_store, comp, store, up, *_ = _upload_all(tmp_path)
    assert up.run_once() == N_WINDOWS
    man1 = store.load_manifest(DS, 0)
    assert len(man1.entries) == N_WINDOWS and man1.generation == 1

    # force a second generation (recompaction drift): bump and swap
    man2 = store.load_manifest(DS, 0)
    man2.generation += 1
    store.put_manifest(man2)        # demotes gen-1 frame to .prev

    # tear the CURRENT manifest mid-frame: reader falls back to .prev,
    # journals the recovery — never silence, never garbage
    path = store._path(f"manifests/{DS}/shard-0")
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    seq0 = journal.next_seq - 1
    rec = store.load_manifest(DS, 0)
    assert rec.generation == man1.generation
    assert {e.object_key for e in rec.entries.values()} \
        == {e.object_key for e in man1.entries.values()}
    kinds = [e["kind"] for e in journal.since(seq0)]
    assert "manifest_recovered" in kinds


# ----------------------------------------------------- upload + retries


def test_upload_retries_through_fault_points_then_succeeds(tmp_path):
    cs, seg_store, comp, store, up, *_ = _upload_all(tmp_path)
    with faults.plan("objectstore.put", "error", first_k=2):
        n = up.run_once()
    assert n == N_WINDOWS
    assert up.retries >= 2 and up.failures == 0
    # uploaded bytes hash-verify straight back out of the store
    man = store.load_manifest(DS, 0)
    for ent in man.entries.values():
        assert len(store.get_object(ent.object_key)) == ent.size_bytes


def test_upload_failure_past_budget_keeps_backlog(tmp_path):
    cs, seg_store, comp, store, up, *_ = _upload_all(tmp_path)
    up.max_attempts = 2
    with faults.plan("objectstore.put", "error", first_k=10_000):
        assert up.run_once() == 0
    assert up.failures >= 1
    assert up.backlog_segments() == N_WINDOWS
    assert up.backlog_age_s() > 0.0
    assert up.probe()["status"] == "degraded"
    # store heals: the next pass drains the backlog
    assert up.run_once() == N_WINDOWS
    assert up.backlog_segments() == 0
    assert up.probe()["status"] == "ok"


def test_replica_dedup_one_uploader_per_rf_group(tmp_path):
    cs, seg_store, comp, ts_grid, vals, now = _compacted(tmp_path)
    store = _obj_store(tmp_path)
    mapper = ShardMapper(1)
    mapper.update_from_event(ShardEvent("IngestionStarted", DS, 0, "A"))
    mapper.register_replica(0, "B")
    up_a = SegmentUploader(store, seg_store, DS, 1, node="A", mapper=mapper)
    up_b = SegmentUploader(store, seg_store, DS, 1, node="B", mapper=mapper)
    up_c = SegmentUploader(store, seg_store, DS, 1, node="C", mapper=mapper)
    assert up_a.should_upload(0)          # first live owner
    assert not up_b.should_upload(0)      # replica defers
    assert not up_c.should_upload(0)      # non-owner never uploads
    up_b.mount()
    assert up_b.run_once() == 0
    up_a.mount()
    assert up_a.run_once() == N_WINDOWS
    # even a RACE converges on one copy: B force-syncing the same shard
    # writes zero new objects (content addressing dedupes)
    n_objects = len(store.list("objects"))
    up_b2 = SegmentUploader(store, seg_store, DS, 1, node="B")
    up_b2.mount()
    up_b2.run_once()
    assert len(store.list("objects")) == n_objects


# ------------------------------------------------- durability ordering


def test_retention_blocked_until_upload_acked(tmp_path):
    cs, seg_store, comp, store, up, ts_grid, vals, now = \
        _upload_all(tmp_path)
    comp.uploader = up
    up.install_prune_guard(cs)
    before = cs.num_chunksets(DS, 0)
    assert before > 0
    # nothing uploaded yet: retention must refuse to prune ANY covered
    # window — a disk loss after prune would otherwise lose acked data
    seq0 = journal.next_seq - 1
    assert comp.enforce_retention(retain_raw_ms=1, now_ms=now) == 0
    assert cs.num_chunksets(DS, 0) == before
    kinds = [e["kind"] for e in journal.since(seq0)]
    assert "retention_blocked_on_upload" in kinds
    # the guard holds even for DIRECT column-store prunes (any code path)
    assert cs.prune_chunks_before(DS, 0, int(ts_grid[-1]) + WINDOW) == 0
    # upload-acked: the same retention pass now prunes everything
    assert up.run_once() == N_WINDOWS
    assert comp.enforce_retention(retain_raw_ms=1, now_ms=now) == before
    assert cs.num_chunksets(DS, 0) == 0


# ----------------------------------------------------- disk-kill rebuild


def _query_engine_over(seg_store, schemas=None):
    mapper = ShardMapper(1)
    mapper.update_from_event(ShardEvent("IngestionStarted", DS, 0, "n"))
    tier = PersistedTier(seg_store, DS, 1,
                         ColdSegmentCache(64 << 20, use_placer=False),
                         schemas=schemas)
    planner = PersistedClusterPlanner(DS, mapper, tier,
                                      spread_provider=SpreadProvider(
                                          default_spread=1))
    return QueryEngine(DS, TimeSeriesMemStore(), mapper, planner=planner)


def _series_map(res):
    assert res.error is None, res.error
    return {k: (tuple(w.tolist()), tuple(v.tolist()))
            for k, w, v in res.series()}


def test_disk_kill_rebuild_is_bit_identical(tmp_path):
    cs, seg_store, comp, store, up, ts_grid, vals, now = \
        _upload_all(tmp_path)
    assert up.run_once() == N_WINDOWS
    start_s, end_s = int(ts_grid[0]) // 1000 + 600, int(ts_grid[-1]) // 1000
    baseline = _series_map(_query_engine_over(seg_store).query_range(
        "sum(m)", start_s, 300, end_s))
    assert baseline

    # the disk dies: every local segment file is gone
    shutil.rmtree(seg_store.seg_dir(DS, 0))
    assert seg_store.list(DS, 0) == []

    # manifest-driven rebuild from the shared store alone
    stats = restore_from_objectstore(store, seg_store, DS, 1)
    assert stats.segments_fetched == N_WINDOWS
    metas = seg_store.list(DS, 0)
    assert len(metas) == N_WINDOWS
    rebuilt = _series_map(_query_engine_over(seg_store).query_range(
        "sum(m)", start_s, 300, end_s))
    assert rebuilt == baseline

    # idempotent: a second restore fetches nothing (everything present)
    stats2 = restore_from_objectstore(store, seg_store, DS, 1)
    assert stats2.segments_fetched == 0
    assert stats2.segments_present == N_WINDOWS


# ----------------------------------------------------- query-only nodes


def test_query_only_node_serves_cold_with_zero_owned_shards(tmp_path):
    from filodb_tpu.parallel.testcluster import make_cold_read_cluster
    cs, seg_store, comp, store, up, ts_grid, vals, now = \
        _upload_all(tmp_path)
    assert up.run_once() == N_WINDOWS
    start_s, end_s = int(ts_grid[0]) // 1000 + 600, int(ts_grid[-1]) // 1000
    baseline = _series_map(_query_engine_over(seg_store).query_range(
        "sum(m)", start_s, 300, end_s))

    c = make_cold_read_cluster(store, num_shards=1, dataset=DS,
                               data_nodes=("data0",),
                               query_nodes=("q1", "q2"))
    try:
        # the query nodes own NOTHING: zero shards assigned, registered
        # as query-capable on the mapper only
        assert c.mapper.query_nodes == ["q1", "q2"]
        for q in ("q1", "q2"):
            assert all(q not in c.mapper.owners(s)
                       for s in range(c.mapper.num_shards))
        assert c.mapper.query_node_table() == [
            {"node": "q1", "role": "query-only"},
            {"node": "q2", "role": "query-only"}]
        # bit-identical to the local disk tier, served via round-robin
        # dispatch across data + query-only nodes paging the shared store
        for _ in range(4):
            res = c.engine.query_range("sum(m)", start_s, 300, end_s)
            assert _series_map(res) == baseline
    finally:
        c.stop()


def test_dead_object_store_degrades_to_flagged_partial(tmp_path):
    from filodb_tpu.query.rangevector import PlannerParams
    cs, seg_store, comp, store, up, ts_grid, vals, now = \
        _upload_all(tmp_path)
    assert up.run_once() == N_WINDOWS
    start_s, end_s = int(ts_grid[0]) // 1000 + 600, int(ts_grid[-1]) // 1000

    eng = _query_engine_over_remote(store)
    pp = PlannerParams(allow_partial_results=True)
    healthy = eng.query_range("sum(m)", start_s, 300, end_s, pp)
    assert healthy.error is None and healthy.partial is False

    # the store dies (every get errors): cold scans degrade to a FLAGGED
    # partial through the typed shard_unavailable path — never a hang,
    # never a silent full.  Engines are built BEFORE the fault arms (a
    # node that can't even mount would 503 at /ready instead).
    eng2 = _query_engine_over_remote(store, ttl_s=1_000.0)
    eng3 = _query_engine_over_remote(store, ttl_s=1_000.0)
    breakers.configure(failure_threshold=2, open_base_s=0.05,
                       open_max_s=0.1, jitter=0.0)
    with faults.plan("objectstore.get", "error", first_k=1_000_000):
        res = eng2.query_range("sum(m)", start_s, 300, end_s, pp)
    assert res.error is None, res.error
    assert res.partial is True
    # without the partial waiver the typed error surfaces instead
    with faults.plan("objectstore.get", "error", first_k=1_000_000):
        strict = eng3.query_range("sum(m)", start_s, 300, end_s)
    assert strict.error is not None


def _query_engine_over_remote(store, ttl_s=5.0):
    mapper = ShardMapper(1)
    mapper.update_from_event(ShardEvent("IngestionStarted", DS, 0, "n"))
    remote = RemoteSegmentStore(store, DS, 1, ttl_s=ttl_s)
    remote.mount()
    tier = PersistedTier(remote, DS, 1,
                         ColdSegmentCache(64 << 20, use_placer=False))
    planner = PersistedClusterPlanner(DS, mapper, tier,
                                      spread_provider=SpreadProvider(
                                          default_spread=1))
    return QueryEngine(DS, TimeSeriesMemStore(), mapper, planner=planner)


def test_remote_store_serves_stale_manifest_when_store_down(tmp_path):
    cs, seg_store, comp, store, up, *_ = _upload_all(tmp_path)
    assert up.run_once() == N_WINDOWS
    remote = RemoteSegmentStore(store, DS, 1, ttl_s=0.0, max_attempts=1)
    remote.mount()
    assert len(remote.list(DS, 0)) == N_WINDOWS
    breakers.configure(failure_threshold=1, open_base_s=5.0,
                       open_max_s=5.0, jitter=0.0)
    with faults.plan("objectstore.get", "error", first_k=1_000_000):
        # list() survives on the stale cached manifest (staleness_s keeps
        # the health verdict honest about how stale)
        metas = remote.list(DS, 0)
        assert len(metas) == N_WINDOWS
        assert remote.staleness_s() >= 0.0
        assert remote.probe()["status"] == "degraded"
        with pytest.raises(ObjectStoreUnavailable):
            remote.load(metas[0])


# ------------------------------------------------- FiloServer wiring


def _filo_config(tmp_path):
    from filodb_tpu.config import FilodbSettings
    cfg = FilodbSettings()
    cfg.store.segment_window_ms = WINDOW
    cfg.store.segment_closed_lag_ms = WINDOW
    cfg.store.segment_retain_raw_ms = 1
    cfg.objectstore.root = str(tmp_path / "objstore")
    cfg.objectstore.retry_base_s = 0.001
    cfg.objectstore.retry_max_s = 0.01
    return cfg


def _filo_ingest_epoch(srv, ts_grid, vals):
    shard = srv.memstore.get_shard("prometheus", 0)
    shard.ingest_columns("gauge", _pks(),
                         np.broadcast_to(ts_grid, (S, len(ts_grid))),
                         {"value": vals})
    shard.flush_all_groups()


def _filo_query(srv, start_s, end_s):
    st, pay = srv.api.handle("GET", "/api/v1/query_range",
                             {"query": "sum(m)", "start": str(start_s),
                              "end": str(end_s), "step": "300"}, b"")
    assert st == 200, pay
    pay.pop("traceID", None)
    return pay


@pytest.mark.slow
def test_filoserver_disk_kill_rebuild_end_to_end(tmp_path):
    """The operations-runbook drill in miniature: compact + upload on
    node 1, wipe its entire store root, boot node 2 on the empty disk —
    the manifests bring every segment back and the query answer is
    byte-identical (traceID stripped)."""
    import time as _time

    from filodb_tpu.standalone import DatasetConfig, FiloServer
    now_ms = int(_time.time() * 1000)
    t0 = (now_ms - 4 * WINDOW) - ((now_ms - 4 * WINDOW) % WINDOW)
    ns = 2 * WINDOW // INTERVAL
    ts_grid = t0 + np.arange(ns, dtype=np.int64) * INTERVAL
    vals = (np.arange(S)[:, None] * 50.0 + (np.arange(ns) % 11)[None, :])
    start_s, end_s = t0 // 1000 + 600, int(ts_grid[-1]) // 1000

    store_root = tmp_path / "node-store"
    cfg = _filo_config(tmp_path)
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     column_store=LocalDiskColumnStore(str(store_root)),
                     meta_store=LocalDiskMetaStore(str(store_root)),
                     config=cfg)
    try:
        assert srv.object_store is not None
        _filo_ingest_epoch(srv, ts_grid, vals)
        # one compaction pass = compact -> upload -> retention; the
        # upload ack must land BEFORE retention prunes the raw chunks
        srv.compaction_schedulers["prometheus"].run_once()
        up = srv.uploaders["prometheus"]
        assert up.uploads == 2 and up.backlog_segments() == 0
        assert srv.column_store.num_chunksets("prometheus", 0) == 0
        assert srv.health.pending_manifest_mounts() == []
        assert "persistence" in srv.health.probes
        assert srv.health.probes["persistence"]()["status"] == "ok"
        baseline = _filo_query(srv, start_s, end_s)
        assert baseline["data"]["result"]
    finally:
        srv.shutdown()

    # the disk dies: chunks.log, segments, meta — everything local goes
    shutil.rmtree(store_root)

    srv2 = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                      column_store=LocalDiskColumnStore(str(store_root)),
                      meta_store=LocalDiskMetaStore(str(store_root)),
                      config=cfg)
    try:
        assert srv2.health.pending_manifest_mounts() == []
        # a trickle of live traffic lands post-rebuild (sets the raw
        # retention floor); the historical range routes to the restored
        # cold tier
        fresh = np.asarray([now_ms], np.int64)
        _filo_ingest_epoch(srv2, fresh, np.full((S, 1), 1.0))
        rebuilt = _filo_query(srv2, start_s, end_s)
        assert rebuilt == baseline
    finally:
        srv2.shutdown()


def test_filoserver_ready_holds_503_when_mount_fails(tmp_path):
    """A node that cannot see the shared tier at boot must not serve:
    the manifest mount stays pending and /ready answers 503."""
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    store_root = tmp_path / "node-store"
    cfg = _filo_config(tmp_path)
    cfg.objectstore.max_attempts = 1
    # seed the shared store with a manifest so the boot mount has
    # something to fail reading
    seed = LocalObjectStore(cfg.objectstore.root)
    from filodb_tpu.persist.objectstore import ShardManifest
    seed.put_manifest(ShardManifest("prometheus", 0, generation=1))
    with faults.plan("objectstore.get", "error", first_k=1_000_000):
        srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                         column_store=LocalDiskColumnStore(str(store_root)),
                         meta_store=LocalDiskMetaStore(str(store_root)),
                         config=cfg)
    try:
        assert srv.health.pending_manifest_mounts() == ["prometheus"]
        from filodb_tpu.utils.health import SERVING
        srv.health.set_phase(SERVING)
        ok, reason = srv.health.ready()
        assert not ok and "manifest mount pending" in reason
        st, _pay = srv.api.handle("GET", "/ready", {})
        assert st == 503
        assert srv.health.probes["persistence"]()["status"] == "degraded"
    finally:
        srv.shutdown()


# -------------------------------------------------------- readiness gate


def test_ready_gates_on_manifest_mount():
    from filodb_tpu.utils.health import SERVING, HealthEvaluator
    h = HealthEvaluator(node_name="n", phase=SERVING)
    ok, _reason = h.ready()
    assert ok
    h.note_manifest_mount(DS, False)
    ok, reason = h.ready()
    assert not ok and "manifest mount pending" in reason
    h.note_manifest_mount(DS, True)
    ok, _reason = h.ready()
    assert ok
