"""Downsample runtime tests (models ref: core/src/test/.../downsample/
ShardDownsamplerSpec, spark-jobs/src/test/.../DownsamplerMainSpec,
DownsampledTimeSeriesShardSpec)."""
import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, GAUGE, PROM_COUNTER
from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.downsample import (DownsampleClusterPlanner,
                                   DownsampledTimeSeriesStore, DownsamplerJob,
                                   ShardDownsampler, downsample_chunk,
                                   ds_dataset_name, period_boundaries)
from filodb_tpu.ingest.generator import counter_batch, gauge_batch
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import SingleClusterPlanner
from filodb_tpu.query.planners import LongTimeRangePlanner
from filodb_tpu.query.rangevector import QueryContext

START = 1_600_000_000_000
RES = 60_000


# ------------------------------------------------------------ downsamplers


# periods are absolute (k*res, (k+1)*res] buckets, so tests use timestamps
# starting one sample into a period boundary
ALIGNED = (START // RES) * RES


def test_period_boundaries_time_marker():
    ts = np.asarray([ALIGNED + (i + 1) * 10_000 for i in range(18)],
                    dtype=np.int64)
    starts = period_boundaries(ts, RES)
    # 10s samples, 1m periods -> a new period every 6 samples
    assert list(starts) == [0, 6, 12]


def test_period_boundaries_counter_marker_splits_at_drop():
    ts = np.asarray([ALIGNED + (i + 1) * 10_000 for i in range(12)],
                    dtype=np.int64)
    vals = np.asarray([1, 2, 3, 4, 5, 6, 7, 1, 2, 3, 4, 5], dtype=np.float64)
    starts = period_boundaries(ts, RES, counter_vals=vals)
    # period boundary at 6 (time) plus reset boundary at 7 (drop)
    assert list(starts) == [0, 6, 7]


def test_downsample_chunk_gauge():
    T = 24
    ts = np.asarray([ALIGNED + (i + 1) * 10_000 for i in range(T)],
                    dtype=np.int64)
    vals = np.arange(T, dtype=np.float64)
    out_ts, out_cols = downsample_chunk(GAUGE, ts, {"value": vals}, RES)
    assert len(out_ts) == 4
    # tTime = last sample of each period
    assert list(out_ts) == [int(ts[5]), int(ts[11]), int(ts[17]), int(ts[23])]
    assert list(out_cols["min"]) == [0, 6, 12, 18]
    assert list(out_cols["max"]) == [5, 11, 17, 23]
    assert list(out_cols["sum"]) == [15, 51, 87, 123]
    assert list(out_cols["count"]) == [6, 6, 6, 6]
    np.testing.assert_allclose(out_cols["avg"],
                               np.asarray([2.5, 8.5, 14.5, 20.5]))


def test_downsample_chunk_counter_preserves_reset():
    ts = np.asarray([ALIGNED + (i + 1) * 10_000 for i in range(12)],
                    dtype=np.int64)
    vals = np.asarray([1, 2, 3, 4, 5, 6, 7, 1, 2, 3, 4, 5], dtype=np.float64)
    out_ts, out_cols = downsample_chunk(PROM_COUNTER, ts,
                                        {"count": vals}, RES)
    # 3 periods: [0..5], [6] (cut short by the drop at 7), [7..11]
    assert list(out_cols["count"]) == [6, 7, 5]
    # the dip 7 -> 1 survives in the dLast sequence so query-time rate
    # correction still sees the reset
    assert out_cols["count"][1] > out_cols["count"][2]


# ---------------------------------------------------- streaming pipeline


def _mk_raw_engine(store, meta, batches):
    ms = TimeSeriesMemStore(column_store=store, meta_store=meta)
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "local"))
    shard = ms.setup("prometheus", 0)
    for b in batches:
        shard.ingest(b)
    eng = QueryEngine("prometheus", ms, mapper)
    return ms, shard, mapper, eng


@pytest.fixture()
def pipeline():
    raw_cs, raw_meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms, shard, mapper, raw_eng = _mk_raw_engine(
        raw_cs, raw_meta, [gauge_batch(20, 720, start_ms=START),
                           counter_batch(10, 720, start_ms=START)])
    dsr = ShardDownsampler(resolutions=(RES,))
    shard.shard_downsampler = dsr
    shard.flush_all_groups()
    ds_store = DownsampledTimeSeriesStore(
        "prometheus", column_store=InMemoryColumnStore(),
        resolutions=(RES,))
    ds_store.setup_shard(0)
    n = ds_store.ingest_downsample_batches(0, dsr.result_batches())
    assert n > 0
    planner = DownsampleClusterPlanner(ds_store, mapper)
    ds_eng = QueryEngine("prometheus", ds_store, mapper, planner=planner)
    return raw_eng, ds_eng


def _vals(res):
    assert res.error is None, res.error
    assert res.blocks, "empty result"
    return np.asarray(res.blocks[0].values)


# evaluation instants on the period grid: a window (t-10m, t] with t aligned
# to the 1m period boundaries covers whole periods, so period-level
# min/max/sum/count reproduce the raw answers exactly
ALIGNED_S = ALIGNED // 1000


def test_ds_min_max_over_time_exact(pipeline):
    raw_eng, ds_eng = pipeline
    for fn in ("min_over_time", "max_over_time", "sum_over_time",
               "count_over_time"):
        q = f'sum({fn}(heap_usage{{_ws_="demo"}}[10m]))'
        raw = _vals(raw_eng.query_range(q, ALIGNED_S + 1260, 300,
                                        ALIGNED_S + 7080))
        ds = _vals(ds_eng.query_range(q, ALIGNED_S + 1260, 300,
                                      ALIGNED_S + 7080))
        np.testing.assert_allclose(ds, raw, rtol=1e-9, err_msg=fn)


def test_ds_counter_rate_close(pipeline):
    raw_eng, ds_eng = pipeline
    q = 'sum(rate(request_total[10m]))'
    raw = _vals(raw_eng.query_range(q, ALIGNED_S + 1260, 300,
                                    ALIGNED_S + 7080))
    ds = _vals(ds_eng.query_range(q, ALIGNED_S + 1260, 300,
                                  ALIGNED_S + 7080))
    both = ~(np.isnan(raw) | np.isnan(ds))
    assert both.any()
    # rate over dLast periods loses intra-period slope detail only at the
    # window edges: close, not exact
    np.testing.assert_allclose(ds[both], raw[both], rtol=0.05)


def test_ds_substitution_is_idempotent(pipeline):
    """Executing the same plan twice must not double-apply the ds-gauge
    function substitution (count_over_time -> sum_over_time over `count`)."""
    _, ds_eng = pipeline
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    plan = query_range_to_logical_plan(
        'sum(count_over_time(heap_usage[10m]))',
        TimeStepParams(ALIGNED_S + 1260, 300, ALIGNED_S + 7080))
    ep = ds_eng.planner.materialize(plan, QueryContext())
    r1 = ep.execute(ds_eng.source)
    r2 = ep.execute(ds_eng.source)
    np.testing.assert_array_equal(np.asarray(r1.blocks[0].values),
                                  np.asarray(r2.blocks[0].values))


# ------------------------------------------------------------- batch job


def test_batch_job_roundtrip():
    raw_cs, raw_meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms, shard, mapper, raw_eng = _mk_raw_engine(
        raw_cs, raw_meta, [gauge_batch(12, 720, start_ms=START)])
    shard.flush_all_groups()

    ds_cs = InMemoryColumnStore()
    job = DownsamplerJob(raw_cs, ds_cs, "prometheus", resolutions=(RES,))
    stats = job.run([0], START, START + 720 * 10_000)
    assert stats.parts_scanned == 12
    assert stats.chunks_written > 0
    assert stats.records_emitted > 0

    ds_store = DownsampledTimeSeriesStore("prometheus", column_store=ds_cs,
                                          resolutions=(RES,))
    ds_store.setup_shard(0)
    assert ds_store.refresh_index(0) == 12
    planner = DownsampleClusterPlanner(ds_store, mapper)
    ds_eng = QueryEngine("prometheus", ds_store, mapper, planner=planner)
    q = 'sum(max_over_time(heap_usage{_ws_="demo"}[10m]))'
    raw = _vals(raw_eng.query_range(q, ALIGNED_S + 1260, 300,
                                    ALIGNED_S + 7080))
    ds = _vals(ds_eng.query_range(q, ALIGNED_S + 1260, 300,
                                  ALIGNED_S + 7080))
    np.testing.assert_allclose(ds, raw, rtol=1e-9)


# -------------------------------------------- long-time-range integration


def test_long_time_range_with_real_downsample_cluster():
    raw_cs, raw_meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms, shard, mapper, raw_eng = _mk_raw_engine(
        raw_cs, raw_meta, [gauge_batch(8, 720, start_ms=START)])
    dsr = ShardDownsampler(resolutions=(RES,))
    shard.shard_downsampler = dsr
    shard.flush_all_groups()
    ds_store = DownsampledTimeSeriesStore(
        "prometheus", column_store=InMemoryColumnStore(), resolutions=(RES,))
    ds_store.setup_shard(0)
    ds_store.ingest_downsample_batches(0, dsr.result_batches())

    # pretend raw retention starts mid-query; downsample covers everything
    earliest_raw = START + 3_600_000
    raw_planner = SingleClusterPlanner("prometheus", mapper)
    ds_planner = DownsampleClusterPlanner(ds_store, mapper)
    ltr = LongTimeRangePlanner(raw_planner, ds_planner,
                               lambda: earliest_raw,
                               lambda: START + 720 * 10_000)

    class _FanoutSource:
        """Route leaf execs to whichever store owns their dataset."""
        def get_shard(self, dataset, shard_num):
            if "::ds::" in dataset:
                return ds_store.get_shard(dataset, shard_num)
            return ms.get_shard(dataset, shard_num)

    q = 'sum(max_over_time(heap_usage[10m]))'
    plan_eng = QueryEngine("prometheus", _FanoutSource(), mapper, planner=ltr)
    res = plan_eng.query_range(q, ALIGNED_S + 1260, 300, ALIGNED_S + 7080)
    stitched = _vals(res)
    raw_all = _vals(raw_eng.query_range(q, ALIGNED_S + 1260, 300,
                                        ALIGNED_S + 7080))
    np.testing.assert_allclose(stitched, raw_all, rtol=1e-9)


def test_downsample_chunk_histogram_counter_reset():
    """prom-histogram's counter(2) period marker must split periods at a
    histogram count reset so hLast never merges across the reset — the
    dip survives for query-time correction (ref:
    DownsamplePeriodMarker.scala:163 counter marker on histogram schemas)."""
    from filodb_tpu.core.schemas import PROM_HISTOGRAM
    T, B = 12, 4
    ts = np.asarray([ALIGNED + (i + 1) * 10_000 for i in range(T)],
                    dtype=np.int64)
    # cumulative bucket counts rising, then a reset (restart) at i=7
    row = np.arange(1, T + 1, dtype=np.float64)
    row[7:] = np.arange(1, T - 6, dtype=np.float64)
    h = row[:, None] * np.arange(1, B + 1, dtype=np.float64)[None, :]
    count = h[:, -1].copy()
    total = count * 7.0
    out_ts, out_cols = downsample_chunk(
        PROM_HISTOGRAM, ts, {"sum": total, "count": count, "h": h}, RES)
    # same 3 periods as the scalar counter case: the drop at i=7 cuts one
    assert len(out_ts) == 3
    assert list(out_cols["count"]) == [count[5], count[6], count[11]]
    # hLast snapshots the LAST histogram of each period; the pre-reset
    # snapshot (period 1) must exceed the post-reset one (period 2)
    np.testing.assert_array_equal(out_cols["h"][1], h[6])
    np.testing.assert_array_equal(out_cols["h"][2], h[11])
    assert (out_cols["h"][1] > out_cols["h"][2]).all()
    # sum column dips too (dLast across the same periods)
    assert out_cols["sum"][1] > out_cols["sum"][2]


def test_bench_downsample_smoke():
    """The downsample bench workload (DownsamplerMain config parity) runs
    and emits a JSON line."""
    import io
    from contextlib import redirect_stdout
    from bench.suite import bench_downsample
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench_downsample(quick=True)
    import json
    line = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["bench"] == "downsample" and line["value"] > 0


def test_long_time_range_batch_matches_individual(monkeypatch):
    """query_range_batch through the tiered LongTimeRangePlanner: batch
    walks BOTH tiers' leaves (raw + downsample, with the ds-gauge
    function substitution applied in the parked gather) and results
    equal per-query execution."""
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    raw_cs, raw_meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms, shard, mapper, raw_eng = _mk_raw_engine(
        raw_cs, raw_meta, [gauge_batch(8, 720, start_ms=START)])
    dsr = ShardDownsampler(resolutions=(RES,))
    shard.shard_downsampler = dsr
    shard.flush_all_groups()
    ds_store = DownsampledTimeSeriesStore(
        "prometheus", column_store=InMemoryColumnStore(), resolutions=(RES,))
    ds_store.setup_shard(0)
    ds_store.ingest_downsample_batches(0, dsr.result_batches())
    earliest_raw = START + 3_600_000
    ltr = LongTimeRangePlanner(
        SingleClusterPlanner("prometheus", mapper),
        DownsampleClusterPlanner(ds_store, mapper),
        lambda: earliest_raw, lambda: START + 720 * 10_000)

    class _FanoutSource:
        def get_shard(self, dataset, shard_num):
            if "::ds::" in dataset:
                return ds_store.get_shard(dataset, shard_num)
            return ms.get_shard(dataset, shard_num)

    eng = QueryEngine("prometheus", _FanoutSource(), mapper, planner=ltr)
    panels = ['sum(max_over_time(heap_usage[10m]))',
              'sum(min_over_time(heap_usage[10m])) by (_ns_)',
              'sum(sum_over_time(heap_usage[10m])) by (dc)']
    args = (ALIGNED_S + 1260, 300, ALIGNED_S + 7080)
    want = [eng.query_range(q, *args) for q in panels]
    got = eng.query_range_batch(panels, *args)
    for q, w, g in zip(panels, want, got):
        assert g.error is None, (q, g.error)
        wm = {str(k): np.asarray(v) for k, _, v in w.series()}
        gm = {str(k): np.asarray(v) for k, _, v in g.series()}
        assert set(gm) == set(wm), q
        for k in wm:
            np.testing.assert_allclose(gm[k], wm[k], rtol=2e-5, atol=1e-4,
                                       equal_nan=True, err_msg=q)
