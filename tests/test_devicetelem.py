"""Device telemetry (ISSUE 18): per-chip kernel ledger, HBM occupancy
model, compile-cache observability, and the health / CLI / ruler
surfaces (utils/devicetelem.py).

The load-bearing invariants:
  - parity by construction: the ledger's per-(device, kernel) seconds
    sum to QueryStats.device_seconds — locally, bottom-up merged, and
    over the wire;
  - the ring is bounded and the per-device counters survive concurrent
    dispatch;
  - HBM gauges reconcile with MirrorPlacer bookings delta-for-delta;
  - an injected recompile storm is attributable (shape + origin in the
    ledger) and flips the health `device` subsystem to degraded;
  - a ruler alert on `device_hbm_booked_bytes` fires end-to-end through
    the `_self_` self-scrape.
"""
import threading
import time

import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.query.rangevector import QueryStats
from filodb_tpu.standalone import DatasetConfig, FiloServer
from filodb_tpu.utils import devicetelem
from filodb_tpu.utils.devicetelem import (DeviceTelemetry, telem,
                                          watched_call)
from filodb_tpu.utils.events import journal
from filodb_tpu.utils.health import DEGRADED, OK, SERVING, HealthEvaluator
from filodb_tpu.utils.metrics import exec_tally, registry, trace_context


@pytest.fixture(autouse=True)
def _clean_telem():
    telem.clear()
    devicetelem.set_enabled(True)
    yield
    telem.clear()
    devicetelem.set_enabled(True)


# ------------------------------------------------------------------ parity

def test_ledger_feeds_exec_tally_in_lockstep():
    """record_dispatch(kind='kernel') feeds the per-thread exec tally's
    device_s AND device_calls with the same seconds, so the per-device
    breakdown can never drift from the scalar (parity by construction)."""
    snap = exec_tally.snapshot()
    try:
        telem.record_dispatch("fused_run", device="chipA",
                              shape="S4xT8", seconds=0.5)
        telem.record_dispatch("fused_run", device="chipA", seconds=0.25)
        telem.record_dispatch("mesh_fused", device="chipB", seconds=0.125)
        assert exec_tally.device_s == pytest.approx(0.875)
        assert exec_tally.device_calls == {
            ("chipA", "fused_run"): [0.75, 2],
            ("chipB", "mesh_fused"): [0.125, 1]}
        split = sum(c[0] for c in exec_tally.device_calls.values())
        assert split == pytest.approx(exec_tally.device_s)
        # transfers/compiles never feed the tally (note_transfer and the
        # compile path own their attribution) — no double count
        telem.record_dispatch("mirror_upload_full", device="chipA",
                              seconds=9.0, kind="transfer", note=False)
        assert exec_tally.device_s == pytest.approx(0.875)
    finally:
        exec_tally.snapshot()
        exec_tally.restore(snap, 0.0)


def test_stats_device_calls_merge_and_wire_parity():
    """Bottom-up merge and the serialize round trip both preserve the
    seconds-sum == device_seconds invariant, and ?stats=true renders the
    per-chip table."""
    from filodb_tpu.parallel import serialize
    s1 = QueryStats(device_seconds=0.5,
                    device_calls={"chipA|fused_run": [0.5, 2]})
    s2 = QueryStats(device_seconds=0.25,
                    device_calls={"chipA|fused_run": [0.125, 1],
                                  "chipB|mesh_fused": [0.125, 1]})
    s1.merge(s2)
    assert s1.device_seconds == pytest.approx(0.75)
    assert s1.device_calls == {"chipA|fused_run": [0.625, 3],
                               "chipB|mesh_fused": [0.125, 1]}
    assert sum(c[0] for c in s1.device_calls.values()) \
        == pytest.approx(s1.device_seconds)
    # over the wire: the generic dataclass codec ships the new field
    rt = serialize.loads(serialize.dumps(s1))
    assert rt.device_calls == s1.device_calls
    assert rt.device_seconds == pytest.approx(s1.device_seconds)
    # ?stats=true shape: device -> kernel -> {seconds, dispatches}
    d = s1.to_dict()["devices"]
    assert d["chipA"]["fused_run"] == {"seconds": 0.625, "dispatches": 3}
    assert d["chipB"]["mesh_fused"]["dispatches"] == 1


def test_kill_switch_skips_ledger_but_never_stats():
    """set_enabled(False) must not change QueryStats.device_seconds —
    stats correctness is not an observability option."""
    snap = exec_tally.snapshot()
    try:
        devicetelem.set_enabled(False)
        telem.record_dispatch("fused_run", device="chipA", seconds=0.5)
        assert exec_tally.device_s == pytest.approx(0.5)
        assert exec_tally.device_calls[("chipA", "fused_run")] == [0.5, 1]
        snap_t = telem.snapshot()
        assert snap_t["devices"] == {} and snap_t["recent"] == []
        assert not snap_t["enabled"]
    finally:
        devicetelem.set_enabled(True)
        exec_tally.snapshot()
        exec_tally.restore(snap, 0.0)


# ------------------------------------------------------------------ ledger

def test_ring_bounded_newest_first():
    t = DeviceTelemetry(max_entries=16)
    for i in range(100):
        t.record_dispatch(f"k{i % 3}", device="chipA",
                          shape=f"S{i}", seconds=0.001, note=False)
    snap = t.snapshot(recent=50)
    assert snap["ledgerSeq"] == 100
    assert snap["ledgerCapacity"] == 16
    assert len(snap["recent"]) == 16
    seqs = [e["seq"] for e in snap["recent"]]
    assert seqs == sorted(seqs, reverse=True) and seqs[0] == 100
    # cumulative counters are NOT ring-bounded
    assert snap["devices"]["chipA"]["dispatches"] == 100
    # filters
    assert all(e["kernel"] == "k0" for e in t.recent(limit=5, kind="")
               if e["kernel"] == "k0")
    only = t.recent(limit=100, device="chipA")
    assert len(only) == 16
    assert t.recent(limit=100, device="nosuch") == []


def test_concurrent_dispatch_keeps_counters_consistent():
    t = DeviceTelemetry(max_entries=4096)
    n_threads, per_thread = 8, 250

    def pump(i):
        for _ in range(per_thread):
            t.record_dispatch("k", device=f"chip{i % 2}",
                              seconds=0.001, bytes_in=10, note=False)

    threads = [threading.Thread(target=pump, args=(i,))
               for i in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    snap = t.snapshot()
    total = n_threads * per_thread
    assert snap["ledgerSeq"] == total
    assert sum(d["dispatches"] for d in snap["devices"].values()) == total
    assert sum(d["bytesIn"] for d in snap["devices"].values()) == total * 10
    busy = sum(d["busySeconds"] for d in snap["devices"].values())
    assert busy == pytest.approx(total * 0.001)
    per_kernel = sum(d["kernels"]["k"]["count"]
                     for d in snap["devices"].values())
    assert per_kernel == total


# ----------------------------------------------------------- HBM occupancy

def test_hbm_gauges_reconcile_with_placer_bookings():
    """Every MirrorPlacer booking delta lands in the telemetry occupancy
    model with the same sign and magnitude — the gauge==booking
    invariant /admin/devices depends on."""
    import jax

    from filodb_tpu.core.devicecache import placer
    dev = jax.local_devices()[0]
    base_p = placer.booked(dev)
    base_t = telem.hbm_booked(dev)
    base_hot = telem.hbm_booked(dev, "hot")
    base_cold = telem.hbm_booked(dev, "cold")
    placer.book(dev, 1 << 20, region="hot")
    placer.book(dev, 2 << 20, region="cold")
    try:
        assert placer.booked(dev) - base_p == 3 << 20
        assert telem.hbm_booked(dev) - base_t == 3 << 20
        assert telem.hbm_booked(dev, "hot") - base_hot == 1 << 20
        assert telem.hbm_booked(dev, "cold") - base_cold == 2 << 20
        g = registry.gauge("device_hbm_booked_bytes",
                           device=str(dev), region="hot")
        assert g.value == telem.hbm_booked(dev, "hot")
    finally:
        placer.book(dev, -(1 << 20), region="hot")
        placer.book(dev, -(2 << 20), region="cold")
    assert placer.booked(dev) - base_p == 0
    assert telem.hbm_booked(dev) - base_t == 0


def test_hbm_high_water_journaled():
    telem.hbm_book("chipHW", "hot", 8 << 20)
    evs = [e for e in journal.since(0, kind="device_hbm_high_water")
           if e.get("device") == "chipHW"]
    assert evs and evs[-1]["bytes"] == 8 << 20
    # gauges clamp at zero on release races
    telem.hbm_book("chipHW", "hot", -(64 << 20))
    assert telem.hbm_booked("chipHW", "hot") == 0


# ------------------------------------------------- compiles + health flip

def test_compile_storm_attributable_and_flips_health():
    """An injected recompile storm (new shapes defeating the jit trace
    cache) lands per-event in the ledger with shape + origin query id,
    fills jit_compile_seconds, and flips the health `device` subsystem
    to degraded while sustained."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x * 2.0)
    origin = "cafebabe" * 4
    count_before = registry.counter("jit_compile_events",
                                    fn="storm_fn").value
    with trace_context(origin):
        for i in range(12):
            x = jnp.zeros((i + 17,))
            res = watched_call("storm_fn", fn, f"S{i + 17}",
                               lambda x=x: fn(x))
            assert res.shape == (i + 17,)
        # same shape again: a cache hit, not a compile
        watched_call("storm_fn", fn, "S17",
                     lambda: fn(jnp.zeros((17,))))
    try:
        compiles = telem.recent(limit=100, kind="compile")
        mine = [e for e in compiles if e["kernel"] == "storm_fn"]
        assert len(mine) == 12
        assert all(e["origin"] == origin for e in mine)
        assert {e["shape"] for e in mine} == {f"S{i + 17}"
                                              for i in range(12)}
        assert registry.counter("jit_compile_events",
                                fn="storm_fn").value - count_before == 12
        evs = [e for e in journal.since(0, kind="jit_compile")
               if e.get("kernel") == "storm_fn"]
        assert len(evs) == 12 and all(e["origin"] == origin for e in evs)
        ev = HealthEvaluator(phase=SERVING)
        dv = ev.evaluate()["subsystems"]["device"]
        assert dv["status"] == DEGRADED
        assert "compile_storm" in dv["reasons"]
        assert dv["recentCompiles"] >= 12
    finally:
        # the storm's journal residue must not degrade later tests'
        # health verdicts (RECENT_WINDOW_S outlives this file)
        journal.clear()
    assert HealthEvaluator(phase=SERVING) \
        ._device_verdict()["status"] == OK


def test_watched_call_disabled_is_passthrough():
    devicetelem.set_enabled(False)
    calls = []
    res = watched_call("k", object(), "S1", lambda: calls.append(1) or 7)
    assert res == 7 and calls == [1]
    assert telem.recent(limit=10) == []


# ------------------------------------------------------------- HTTP route

def _server(selfmon=False, rules_groups=None):
    cfg = FilodbSettings()
    if selfmon:
        cfg.selfmon.enabled = True
        cfg.selfmon.interval_s = 3600.0    # manual scrape_once in tests
    if rules_groups is not None:
        cfg.rules.enabled = True
        cfg.rules.groups = rules_groups
    return FiloServer([DatasetConfig("prometheus", num_shards=2)],
                      config=cfg)


def test_admin_devices_route():
    srv = _server()
    try:
        telem.record_dispatch("probe_kernel", device="chipZ",
                              shape="S4xT8", seconds=0.01,
                              origin="deadbeef", note=False)
        telem.record_dispatch("probe_compile", device="chipZ",
                              kind="compile", note=False)
        telem.hbm_book("chipZ", "hot", 12345)
        st, p = srv.api.handle("GET", "/admin/devices", {})
        assert st == 200 and p["status"] == "success"
        dev = p["data"]["devices"]["chipZ"]
        assert dev["dispatches"] == 2
        assert dev["compiles"] == 1
        assert dev["hbm"]["hot"] == 12345
        assert dev["kernels"]["probe_kernel"]["count"] == 1
        kernels = [e["kernel"] for e in p["data"]["recent"]]
        assert "probe_kernel" in kernels
        # filters
        st, p = srv.api.handle("GET", "/admin/devices",
                               {"kind": "compile", "recent": "50"})
        assert st == 200
        assert all(e["kind"] == "compile" for e in p["data"]["recent"])
        st, p = srv.api.handle("GET", "/admin/devices",
                               {"device": "nosuch"})
        assert st == 200 and p["data"]["recent"] == []
        st, _ = srv.api.handle("GET", "/admin/devices", {"recent": "x"})
        assert st == 400
    finally:
        srv.shutdown()


# -------------------------------------------------------- ruler alert e2e

def test_hbm_alert_fires_through_self_scrape_end_to_end():
    """The conf/example-filodb.conf device_telemetry alert group, proven
    live: HBM booking -> device_hbm_booked_bytes gauge -> `_self_`
    scrape -> ruler eval through the ordinary frontend -> firing at
    /api/v1/alerts; release resolves it."""
    groups = {"device_telemetry": {
        "interval": 10,
        "rules": {"hbm_pressure": {
            "alert": "DeviceHbmPressure",
            "expr": 'max by (device) '
                    '(device_hbm_booked_bytes{job="filodb"}) '
                    '> 1500000',
            "labels": {"severity": "page"},
        }}}}
    srv = _server(selfmon=True, rules_groups=groups)
    try:
        telem.hbm_book("chipAlert", "hot", 2_000_000)
        srv.selfmon.scrape_once()
        assert srv.ruler.evaluate_group("device_telemetry",
                                        ts=time.time() + 1)
        st, p = srv.api.handle("GET", "/api/v1/alerts", {})
        assert st == 200
        mine = [a for a in p["data"]["alerts"]
                if a["labels"].get("device") == "chipAlert"]
        assert len(mine) == 1
        assert mine[0]["labels"]["alertname"] == "DeviceHbmPressure"
        assert mine[0]["state"] == "firing"
        # release drops the gauge; the next scrape + eval resolves
        telem.hbm_book("chipAlert", "hot", -2_000_000)
        srv.selfmon.scrape_once()
        assert srv.ruler.evaluate_group("device_telemetry",
                                        ts=time.time() + 2)
        st, p = srv.api.handle("GET", "/api/v1/alerts", {})
        assert not [a for a in p["data"]["alerts"]
                    if a["labels"].get("device") == "chipAlert"]
    finally:
        srv.shutdown()


# ----------------------------------------------------- snapshot semantics

def test_snapshot_includes_hbm_only_devices_and_decays_ewma():
    telem.hbm_book("chipIdle", "cold", 4096)
    snap = telem.snapshot()
    assert snap["devices"]["chipIdle"]["hbm"]["cold"] == 4096
    assert snap["devices"]["chipIdle"]["dispatches"] == 0
    # a busy burst reads nonzero utilization, and the snapshot decays it
    # toward idle without needing further traffic
    telem.record_dispatch("k", device="chipBusy", seconds=3.0, note=False)
    u0 = telem.snapshot()["devices"]["chipBusy"]["utilEwma"]
    assert u0 > 0.0
    with telem._lock:
        telem._devices["chipBusy"].last_unix_s -= 120.0
    u1 = telem.snapshot()["devices"]["chipBusy"]["utilEwma"]
    assert u1 < u0
