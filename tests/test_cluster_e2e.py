"""Multi-process cluster end-to-end (ref: standalone/src/multi-jvm/
IngestionAndRecoverySpec.scala, ClusterSingletonFailoverSpec.scala).

Three REAL node processes join a coordinator, receive shard assignments,
ingest the same stream (each keeping only its shards, the Kafka-partition
stand-in), serve a cross-node scatter-gather query — then one node is
SIGKILLed, the liveness monitor detects the death, shards reassign to the
standby node, which recovers from the shared column store, and the query
completes with full results again.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.gateway.influx import influx_lines_to_batches
from filodb_tpu.gateway.router import split_batch_by_shard
from filodb_tpu.parallel.cluster import ClusterClient, ClusterCoordinator, _rpc
from filodb_tpu.parallel.shardmanager import ShardManager
from filodb_tpu.parallel.shardmapper import SpreadProvider
from filodb_tpu.parallel.transport import RemoteNodeDispatcher
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import SingleClusterPlanner

START = 1_600_000_000_000
NUM_SHARDS = 4
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_lines(num_series=24, num_samples=90):
    lines = []
    for t in range(num_samples):
        ts_ns = (START + t * 10_000) * 1_000_000
        for i in range(num_series):
            lines.append(
                f"cluster_metric,_ws_=demo,_ns_=App-{i % 4},inst=i{i} "
                f"value={t * 3.0 + i} {ts_ns}")
    return lines


def _spawn(name, coord_port, data_dir, store_url=""):
    # stderr to a file, never a PIPE: an undrained pipe filling up would
    # block the node's writes and stall heartbeats mid-test
    os.makedirs(str(data_dir), exist_ok=True)
    errpath = os.path.join(str(data_dir), f"{name}.stderr")
    cmd = [sys.executable, "-m", "filodb_tpu.parallel.nodeapp",
           "--name", name, "--coordinator", f"127.0.0.1:{coord_port}",
           "--data-dir", str(data_dir), "--platform", "cpu",
           "--heartbeat-interval", "0.3"]
    if store_url:
        cmd += ["--store-url", store_url]
    with open(errpath, "w") as errf:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=errf, text=True,
            cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        # the child holds its own duplicated fd; the parent's closes now
    box = {}

    def _read():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=_read, daemon=True)
    t.start()
    t.join(timeout=90)
    if "line" not in box or not box["line"]:
        proc.kill()
        with open(errpath) as f:
            tail = f.read()[-2000:]
        raise RuntimeError(f"node {name} failed to start: {tail}")
    info = json.loads(box["line"])
    assert info["ready"]
    return proc, info


def _wait_state(cli, pred, timeout_s=30.0, what="condition"):
    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        last = cli.state()
        if pred(last):
            return last
        time.sleep(0.3)
    raise AssertionError(f"timed out waiting for {what}; last state: {last}")


def _engine(cli):
    mapper, addrs = cli.mapper("prometheus")
    spread = SpreadProvider(default_spread=1)
    planner = SingleClusterPlanner(
        "prometheus", mapper, spread,
        dispatcher_factory=lambda s: RemoteNodeDispatcher(
            *addrs[mapper.node_for_shard(s)]))
    return QueryEngine("prometheus", TimeSeriesMemStore(), mapper,
                       planner=planner)


def _query(cli, q):
    res = _engine(cli).query_range(q, START // 1000 + 120, 60,
                                   START // 1000 + 880)
    assert res.error is None, res.error
    return {str(k): np.asarray(v) for k, _, v in res.series()}


@pytest.mark.parametrize("backend", ["shared_dir", "netstore"])
def test_cluster_ingest_query_failover(tmp_path, backend):
    # netstore: nodes get PRIVATE data dirs and reach one central chunk
    # service over TCP — failover recovery with NO shared filesystem,
    # the reference's Cassandra topology (CassandraColumnStore.scala:53-80)
    svc = None
    store_url = ""
    if backend == "netstore":
        from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                                   LocalDiskMetaStore)
        from filodb_tpu.persist.netstore import ChunkServiceServer
        root = str(tmp_path / "central_store")
        svc = ChunkServiceServer(LocalDiskColumnStore(root),
                                 LocalDiskMetaStore(root)).start()
        store_url = f"127.0.0.1:{svc.address[1]}"

    def node_dir(name):
        return tmp_path if backend == "shared_dir" else tmp_path / name

    sm = ShardManager(reassignment_min_interval_s=0)
    coord = ClusterCoordinator(sm, liveness_timeout_s=2.5,
                               check_interval_s=0.3).start()
    coord.setup_dataset("prometheus", NUM_SHARDS, min_num_nodes=2)
    procs = []
    try:
        pa, ia = _spawn("A", coord.address[1], node_dir("A"), store_url)
        procs.append(pa)
        pb, ib = _spawn("B", coord.address[1], node_dir("B"), store_url)
        procs.append(pb)
        pc, ic = _spawn("C", coord.address[1], node_dir("C"), store_url)
        procs.append(pc)
        cli = ClusterClient(coord.address)

        # A and B each own 2 shards and report them active; C is standby
        st = _wait_state(
            cli, lambda s: s["datasets"]["prometheus"]["statuses"]
            == ["Active"] * NUM_SHARDS, what="all shards active")
        owners = set(st["datasets"]["prometheus"]["nodes"])
        assert owners == {"A", "B"}

        # same stream to every node; each ingests only its shards
        lines = _mk_lines()
        for info in (ia, ib, ic):
            r = _rpc(("127.0.0.1", info["control_port"]),
                     {"cmd": "ingest_lines", "lines": lines, "offset": 1},
                     timeout_s=120)
            assert r["ok"], r
        total = sum(
            _rpc(("127.0.0.1", info["control_port"]), {"cmd": "ping"})["ok"]
            for info in (ia, ib, ic))
        assert total == 3

        # ground truth: a local store ingesting the identical stream
        truth = TimeSeriesMemStore()
        t_mapper, _ = cli.mapper("prometheus")
        spread = SpreadProvider(default_spread=1)
        for s in range(NUM_SHARDS):
            truth.setup("prometheus", s)
        for batch in influx_lines_to_batches(lines):
            for s, sub in split_batch_by_shard(batch, t_mapper,
                                               spread).items():
                truth.get_shard("prometheus", s).ingest(sub)
        truth_eng = QueryEngine("prometheus", truth, t_mapper, spread)
        want_res = truth_eng.query_range(
            'sum by (_ns_)(cluster_metric{_ws_="demo"})',
            START // 1000 + 120, 60, START // 1000 + 880)
        want = {str(k): np.asarray(v) for k, _, v in want_res.series()}
        assert len(want) == 4

        got = _query(cli, 'sum by (_ns_)(cluster_metric{_ws_="demo"})')
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-9,
                                       equal_nan=True)

        # persist everything, then kill node B without ceremony
        for info in (ia, ib):
            r = _rpc(("127.0.0.1", info["control_port"]), {"cmd": "flush"},
                     timeout_s=120)
            assert r["ok"], r
        pb.kill()

        # deathwatch: B leaves the member list, its shards land on C and
        # come back Active after index recovery
        def _failover_done(s):
            ds = s["datasets"]["prometheus"]
            return ("B" not in s["members"]
                    and set(ds["nodes"]) == {"A", "C"}
                    and ds["statuses"] == ["Active"] * NUM_SHARDS)
        _wait_state(cli, _failover_done, timeout_s=60,
                    what="failover to standby node C")

        # the same query now scatter-gathers across A + C, paging B's
        # flushed history from the shared column store
        got2 = _query(cli, 'sum by (_ns_)(cluster_metric{_ws_="demo"})')
        assert set(got2) == set(want)
        for k in want:
            np.testing.assert_allclose(got2[k], want[k], rtol=1e-9,
                                       equal_nan=True)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        coord.stop()
        if svc is not None:
            svc.stop()


def test_mid_query_node_kill_semantics(tmp_path):
    """Round-5 verdict item 4 (ref: ClusterSingletonFailoverSpec.scala,
    PlanDispatcher.scala:31-55): SIGKILL a shard owner with queries in
    flight.  The scatter-gather root must (a) surface a CLEAN typed
    QueryError — code `shard_unavailable` — promptly, never hang;
    (b) return flagged partials when the caller opted in, never silent
    ones; (c) with a replan hook, retry on the reassigned owner after
    failover and succeed."""
    from filodb_tpu.query.rangevector import PlannerParams

    q = 'sum by (_ns_)(cluster_metric{_ws_="demo"})'
    sm = ShardManager(reassignment_min_interval_s=0)
    coord = ClusterCoordinator(sm, liveness_timeout_s=2.5,
                               check_interval_s=0.3).start()
    coord.setup_dataset("prometheus", NUM_SHARDS, min_num_nodes=2)
    procs = []
    try:
        pa, ia = _spawn("A", coord.address[1], tmp_path)
        procs.append(pa)
        pb, ib = _spawn("B", coord.address[1], tmp_path)
        procs.append(pb)
        pc, ic = _spawn("C", coord.address[1], tmp_path)
        procs.append(pc)
        cli = ClusterClient(coord.address)
        _wait_state(
            cli, lambda s: s["datasets"]["prometheus"]["statuses"]
            == ["Active"] * NUM_SHARDS, what="all shards active")

        lines = _mk_lines()
        for info in (ia, ib, ic):
            r = _rpc(("127.0.0.1", info["control_port"]),
                     {"cmd": "ingest_lines", "lines": lines, "offset": 1},
                     timeout_s=120)
            assert r["ok"], r
        for info in (ia, ib):
            r = _rpc(("127.0.0.1", info["control_port"]), {"cmd": "flush"},
                     timeout_s=120)
            assert r["ok"], r

        # engines bound to the PRE-KILL shard map: they will keep
        # dispatching to B after it dies (the production window between
        # a crash and deathwatch noticing)
        stale_engine = _engine(cli)
        want = _query(cli, q)
        assert len(want) == 4

        # (true in-flight race) fire a query concurrently with the kill:
        # it must COMPLETE either way — success if it won the race, a
        # typed error if it lost — never hang
        box = {}

        def racing():
            box["res"] = stale_engine.query_range(
                q, START // 1000 + 120, 60, START // 1000 + 880)

        racer = threading.Thread(target=racing, daemon=True)
        racer.start()
        time.sleep(0.05)
        pb.kill()
        racer.join(timeout=30)
        assert "res" in box, "in-flight query hung after owner SIGKILL"
        res = box["res"]
        assert res.error is None or res.error.startswith(
            ("shard_unavailable", "dispatch_timeout")), res.error

        # (a) clean typed error, promptly — before failover completes
        t0 = time.time()
        res = stale_engine.query_range(q, START // 1000 + 120, 60,
                                       START // 1000 + 880)
        elapsed = time.time() - t0
        assert res.error is not None and res.error.startswith(
            "shard_unavailable"), res.error
        assert elapsed < 20, f"error took {elapsed:.1f}s (hang?)"

        # (b) flagged partials on opt-in: surviving shards answer, the
        # result says so — silent partials are forbidden
        res_p = stale_engine.query_range(
            q, START // 1000 + 120, 60, START // 1000 + 880,
            PlannerParams(allow_partial_results=True))
        assert res_p.error is None, res_p.error
        assert res_p.partial is True
        assert 0 < res_p.num_series <= len(want)
        payload = QueryEngine.to_prom_matrix(res_p)
        assert payload.get("partial") is True
        assert payload.get("warnings")

        # (c) replan hook: same stale engine, but wired to re-plan from a
        # fresh shard map — after failover lands the retry succeeds
        def _failover_done(s):
            ds = s["datasets"]["prometheus"]
            return ("B" not in s["members"]
                    and ds["statuses"] == ["Active"] * NUM_SHARDS)
        _wait_state(cli, _failover_done, timeout_s=60,
                    what="failover to standby")

        retry_engine = _engine(cli2 := ClusterClient(coord.address))
        # poison the retry engine with the STALE planner so its first
        # dispatch fails, then let the hook re-plan from the live map
        retry_engine.planner = stale_engine.planner
        retry_engine.replan_hook = lambda: _engine(cli2).planner
        res3 = retry_engine.query_range(q, START // 1000 + 120, 60,
                                        START // 1000 + 880)
        assert res3.error is None, res3.error
        got3 = {str(k): np.asarray(v) for k, _, v in res3.series()}
        assert set(got3) == set(want)
        for k in want:
            np.testing.assert_allclose(got3[k], want[k], rtol=1e-9,
                                       equal_nan=True)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        coord.stop()
