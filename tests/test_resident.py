"""Compressed resident tier + memory enforcement (ref: the reference's
in-memory compressed chunk retention doc/ingestion.md:110, headroom task
TimeSeriesShard.scala:1665, PartitionEvictionPolicy.scala:59)."""
import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.ingest.generator import counter_batch
from filodb_tpu.memory.chunks import encode_chunkset
from filodb_tpu.memory.resident import ResidentChunkCache
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             SpreadProvider)
from filodb_tpu.query.engine import QueryEngine

START_MS = 1_600_000_000_000
T = 400


def _mk_engine_and_shard(num_series=20, config=None):
    ms = TimeSeriesMemStore(config=config)
    shard = ms.setup("prometheus", 0)
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "local"))
    shard.ingest(counter_batch(num_series, T, start_ms=START_MS))
    engine = QueryEngine("prometheus", ms, mapper, SpreadProvider(0))
    return engine, shard


def _query(engine):
    start_s = START_MS // 1000 + 600
    end_s = START_MS // 1000 + (T - 1) * 10
    res = engine.query_range('sum(rate(request_total[5m]))',
                             start_s, 60, end_s)
    assert res.error is None
    return np.asarray(res.blocks[0].values)


def test_flush_populates_resident_cache():
    _, shard = _mk_engine_and_shard()
    assert shard.resident.num_chunks == 0
    shard.flush_all_groups()
    assert shard.resident.num_chunks == 20
    assert shard.resident.bytes_used > 0
    # compression of the encoded PAYLOAD: far below the 16 B/sample dense
    # footprint (bytes_used additionally carries per-chunk object overhead
    # so the eviction budget reflects true RSS cost)
    payload = shard.resident.bytes_used \
        - 20 * shard.resident.CHUNK_OVERHEAD
    bytes_per_sample = payload / (20 * T)
    assert bytes_per_sample < 8, bytes_per_sample


def test_enforce_memory_truncates_dense_and_queries_still_correct():
    engine, shard = _mk_engine_and_shard()
    before = _query(engine)
    usage0 = shard.memory_usage()

    released = shard.enforce_memory(budget_bytes=1, active_tail_rows=64)
    assert released > 0
    usage1 = shard.memory_usage()
    assert usage1["dense_bytes"] < usage0["dense_bytes"]
    store = shard.stores["prom-counter"]
    assert store.time_used <= 64

    # NullColumnStore is the default here: history can ONLY come from the
    # compressed RAM tier — this proves the page-in path never hit disk
    after = _query(engine)
    np.testing.assert_allclose(after, before, rtol=1e-9)


def test_enforce_memory_noop_under_budget():
    _, shard = _mk_engine_and_shard()
    assert shard.enforce_memory(budget_bytes=1 << 40) == 0


def test_resident_budget_evicts_oldest_first():
    cache = ResidentChunkCache(budget_bytes=0)  # set after sizing
    ts = np.arange(100, dtype=np.int64) * 1000
    vals = np.cumsum(np.ones(100))
    sizes = []
    chunks = []
    for i in range(10):
        cs = encode_chunkset(ts + i * 100_000, {"count": vals},
                             {"count": "double"}, ingestion_time_ms=i)
        chunks.append(cs)
        sizes.append(cs.nbytes)
    cache.budget_bytes = (sum(sizes[:5])
                          + 5 * ResidentChunkCache.CHUNK_OVERHEAD
                          + 1)                # room for ~5 chunks
    for i, cs in enumerate(chunks):
        cache.add(0, cs)
    assert cache.bytes_used <= cache.budget_bytes
    assert cache.chunks_evicted >= 5
    # survivors are the NEWEST chunks
    floors = [c.info.start_time_ms for c in cache.read(0, 0, 1 << 60)]
    assert min(floors) > chunks[2].info.start_time_ms


def test_drop_part_releases_bytes():
    cache = ResidentChunkCache(budget_bytes=1 << 30)
    ts = np.arange(50, dtype=np.int64) * 1000
    cs = encode_chunkset(ts, {"count": np.ones(50)}, {"count": "double"}, 0)
    cache.add(7, cs)
    assert cache.bytes_used > 0
    cache.drop_part(7)
    assert cache.bytes_used == 0
    assert cache.read(7, 0, 1 << 60) == []


def test_evicted_partition_drops_resident_chunks():
    _, shard = _mk_engine_and_shard(num_series=5)
    shard.flush_all_groups()
    assert shard.resident.num_chunks == 5
    # mark every series ended long ago, then evict
    for info in shard.partitions:
        shard.index.update_end_time(info.part_id, START_MS)
    n = shard.evict_ended_partitions(START_MS + 1)
    assert n == 5
    assert shard.resident.bytes_used == 0


def test_memory_usage_accounting():
    _, shard = _mk_engine_and_shard()
    u = shard.memory_usage()
    assert u["dense_bytes"] > 0
    assert u["resident_bytes"] == 0
    shard.flush_all_groups()
    u2 = shard.memory_usage()
    assert u2["resident_bytes"] > 0
    assert u2["total_bytes"] == u2["dense_bytes"] + u2["resident_bytes"]
