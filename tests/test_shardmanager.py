"""ShardManager tests (models ref: coordinator/src/test/.../ShardManagerSpec,
ShardAssignmentStrategySpec — assignment/failover without a real network)."""
import pytest

from filodb_tpu.parallel.shardmanager import (DatasetResourceSpec,
                                              ShardManager, ShardSnapshot)
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardStatus

DS = "prometheus"
RES = DatasetResourceSpec(num_shards=8, min_num_nodes=2)


def _mgr(t0=1000.0):
    state = {"now": t0}
    m = ShardManager(reassignment_min_interval_s=600.0,
                     clock=lambda: state["now"])
    return m, state


def test_even_assignment_across_nodes():
    mgr, _ = _mgr()
    mgr.add_member("nodeA")
    mgr.add_member("nodeB")
    mapper = mgr.setup_dataset(DS, RES)
    assert sorted(mapper.shards_for_node("nodeA") +
                  mapper.shards_for_node("nodeB")) == list(range(8))
    assert len(mapper.shards_for_node("nodeA")) == 4
    assert len(mapper.shards_for_node("nodeB")) == 4
    assert all(s == ShardStatus.ASSIGNED for s in mapper.statuses)


def test_join_after_setup_takes_unassigned():
    mgr, _ = _mgr()
    mgr.add_member("nodeA")
    mapper = mgr.setup_dataset(DS, RES)
    # capacity ceil(8/2)=4: half the shards wait for a second node
    assert len(mapper.shards_for_node("nodeA")) == 4
    assert mapper.num_assigned == 4
    got = mgr.add_member("nodeB")
    assert len(got[DS]) == 4
    assert mapper.num_assigned == 8


def test_excess_nodes_get_nothing_until_needed():
    mgr, _ = _mgr()
    for n in ("a", "b", "c"):
        mgr.add_member(n)
    mapper = mgr.setup_dataset(DS, RES)
    assert mapper.num_assigned == 8
    counts = sorted(len(mapper.shards_for_node(n)) for n in ("a", "b", "c"))
    assert counts == [0, 4, 4]      # reverse deploy order fills newest first


def test_failover_reassigns_downed_shards():
    mgr, state = _mgr()
    mgr.add_member("nodeA")
    mgr.add_member("nodeB")
    mgr.add_member("nodeC")         # spare capacity
    mapper = mgr.setup_dataset(DS, RES)
    lost = mapper.shards_for_node("nodeB") or mapper.shards_for_node("nodeC")
    owner = "nodeB" if mapper.shards_for_node("nodeB") else "nodeC"
    affected = mgr.remove_member(owner)
    assert affected[DS] == lost
    # reassigned to the spare node — nothing left unassigned
    assert mapper.num_assigned == 8
    assert not mapper.shards_for_node(owner)


def test_reassignment_rate_limit():
    mgr, state = _mgr()
    mgr.add_member("a")
    mgr.add_member("b")
    mapper = mgr.setup_dataset(DS, RES)
    # kill b; no spare node -> shards stay down
    mgr.remove_member("b")
    assert mapper.num_assigned == 4
    mgr.add_member("c")             # c picks the downed shards up (first move)
    assert mapper.num_assigned == 8
    # kill c immediately: the same shards just moved; rate limit blocks
    mgr.remove_member("c")
    mgr.add_member("d")
    assert mapper.num_assigned == 4, "rate limit should block immediate move"
    # ... until the interval passes
    state["now"] += 601.0
    mgr.add_member("e")
    assert mapper.num_assigned == 8


def test_rate_limited_shards_do_not_block_eligible_ones():
    """A rate-limited shard must not occupy the proposal window: eligible
    shards beyond the capacity-truncated pool still get assigned."""
    mgr, state = _mgr()
    res4 = DatasetResourceSpec(num_shards=4, min_num_nodes=2)
    mgr.add_member("n1")
    mgr.add_member("n2")
    mapper = mgr.setup_dataset(DS, res4)
    # shards 0,1 (n2's) bounce: n2 dies, n3 picks them up, n3 dies
    first = mapper.shards_for_node("n2")
    mgr.remove_member("n2")
    mgr.add_member("n3")
    assert sorted(mapper.shards_for_node("n3")) == sorted(first)
    mgr.remove_member("n3")          # `first` now rate-limited
    # n1's shards also go down (n1 dies), then n4 joins: it must take n1's
    # shards even though `first` sits earlier in the unassigned pool
    second = mapper.shards_for_node("n1")
    mgr.remove_member("n1")
    mgr.add_member("n4")
    assert sorted(mapper.shards_for_node("n4")) == sorted(second), \
        "rate-limited shards blocked eligible ones"


def test_subscriber_gets_snapshot_then_events():
    mgr, _ = _mgr()
    mgr.add_member("a")
    mgr.add_member("b")
    mgr.setup_dataset(DS, RES)
    got = []
    mgr.subscribe(DS, got.append)
    assert isinstance(got[0], ShardSnapshot)
    assert got[0].statuses == ["Assigned"] * 8
    mgr.on_shard_event(ShardEvent("IngestionStarted", DS, 0, "a"))
    assert isinstance(got[-1], ShardEvent)
    assert got[-1].kind == "IngestionStarted"
    assert mgr.mapper(DS).statuses[0] == ShardStatus.ACTIVE


def test_error_shard_returns_to_pool_and_reassigns():
    mgr, state = _mgr()
    mgr.add_member("a")
    mgr.add_member("b")
    mgr.add_member("c")
    mapper = mgr.setup_dataset(DS, RES)
    victim = mapper.shards_for_node("b")[0] if mapper.shards_for_node("b") \
        else mapper.shards_for_node("c")[0]
    owner = mapper.node_for_shard(victim)
    mgr.on_shard_event(ShardEvent("IngestionError", DS, victim, owner))
    # shard moved to a node with spare capacity
    assert mapper.node_for_shard(victim) is not None
    assert mapper.node_for_shard(victim) != owner


def test_singleton_recovery_from_snapshots():
    mgr, _ = _mgr()
    mgr.add_member("a")
    mgr.add_member("b")
    mapper = mgr.setup_dataset(DS, RES)
    for s in range(8):
        mgr.on_shard_event(ShardEvent("IngestionStarted", DS, s,
                                      mapper.node_for_shard(s)))
    snap = mgr.snapshot(DS)

    # new singleton after failover
    mgr2, _ = _mgr()
    mgr2.recover({DS: RES}, ["a", "b"], {DS: snap})
    m2 = mgr2.mapper(DS)
    assert m2.nodes == mapper.nodes
    assert [s.value for s in m2.statuses] == ["Active"] * 8


def test_recovery_assigns_leftovers():
    mgr, _ = _mgr()
    snap = ShardSnapshot(DS, [None] * 8, ["Unassigned"] * 8)
    mgr.recover({DS: RES}, ["a", "b"], {DS: snap})
    assert mgr.mapper(DS).num_assigned == 8
