"""xxHash known-answer tests (standard XXH32/XXH64 vectors)."""
from filodb_tpu.utils.hashing import xxhash32, xxhash64, hash32_signed


def test_xxhash32_vectors():
    assert xxhash32(b"") == 0x02CC5D05
    assert xxhash32(b"abc") == 0x32D153FF
    assert xxhash32(b"", seed=1) != xxhash32(b"")
    # >16 bytes exercises the 4-lane path
    assert xxhash32(b"0123456789abcdef0123") == xxhash32(b"0123456789abcdef0123")


def test_xxhash64_vectors():
    assert xxhash64(b"") == 0xEF46DB3751D8E999
    assert xxhash64(b"abc") == 0x44BC2CF5AD770999


def test_hash32_signed_range():
    for data in [b"", b"a", b"foo_bar_metric", b"x" * 100]:
        h = hash32_signed(data)
        assert -(1 << 31) <= h < (1 << 31)
        assert (h & 0xFFFFFFFF) == xxhash32(data)


def test_determinism_across_lengths():
    seen = set()
    for i in range(64):
        h = xxhash32(bytes(range(i)))
        assert h not in seen
        seen.add(h)
