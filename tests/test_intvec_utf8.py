"""Bit-packed int vectors + UTF8/dict vectors — property-style round trips
(mirrors ref memory/src/test/.../EncodingPropertiesTest.scala,
IntBinaryVectorTest, UTF8VectorTest)."""
import numpy as np
import pytest

from filodb_tpu.memory import intvec, utf8vec


RNG = np.random.default_rng(42)


@pytest.mark.parametrize("span_bits", [0, 1, 2, 3, 7, 9, 15, 17, 31, 40])
def test_intvec_roundtrip_widths(span_bits):
    n = 1000
    base = int(RNG.integers(-(1 << 40), 1 << 40))
    vals = base + RNG.integers(0, (1 << span_bits) if span_bits else 1,
                               size=n).astype(np.int64)
    enc = intvec.pack_ints(vals)
    out = intvec.unpack_ints(enc, n)
    np.testing.assert_array_equal(out, vals)


def test_intvec_const_is_tiny():
    vals = np.full(10_000, 123456789, dtype=np.int64)
    enc = intvec.pack_ints(vals)
    assert len(enc) == 10  # header only
    assert intvec.packed_width_bits(enc) == 0
    np.testing.assert_array_equal(intvec.unpack_ints(enc, 10_000), vals)


def test_intvec_width_selection():
    # span 3 -> 2 bits, span 200 -> 8 bits, span 70000 -> 32 bits
    for span, bits in [(3, 2), (10, 4), (200, 8), (60_000, 16),
                       (70_000, 32), (1 << 40, 64)]:
        enc = intvec.pack_ints(np.array([5, 5 + span], dtype=np.int64))
        assert intvec.packed_width_bits(enc) == bits, span


def test_intvec_2bit_packing_density():
    vals = RNG.integers(0, 4, size=4000).astype(np.int64)
    enc = intvec.pack_ints(vals)
    # 4000 values at 2 bits = 1000 bytes + 10 header
    assert len(enc) <= 1024
    np.testing.assert_array_equal(intvec.unpack_ints(enc, 4000), vals)


def test_intvec_empty_and_single():
    assert len(intvec.unpack_ints(intvec.pack_ints(np.array([], np.int64)), 0)) == 0
    one = np.array([-7], dtype=np.int64)
    np.testing.assert_array_equal(
        intvec.unpack_ints(intvec.pack_ints(one), 1), one)


def test_intvec_negative_range():
    vals = RNG.integers(-1000, -900, size=333).astype(np.int64)
    np.testing.assert_array_equal(
        intvec.unpack_ints(intvec.pack_ints(vals), 333), vals)


def test_intvec_masked_roundtrip():
    n = 257
    vals = RNG.integers(0, 100, size=n).astype(np.int64)
    valid = RNG.random(n) < 0.7
    enc = intvec.pack_ints_masked(vals, valid)
    out, out_valid = intvec.unpack_ints_masked(enc, n)
    np.testing.assert_array_equal(out_valid, valid)
    np.testing.assert_array_equal(out[valid], vals[valid])
    assert (out[~valid] == 0).all()


def test_utf8_blob_roundtrip():
    strings = [b"", b"a", "héllo".encode(), b"x" * 1000, b"tail"]
    data = utf8vec.pack_utf8(strings)
    out, off = utf8vec.unpack_utf8(data)
    assert out == strings and off == len(data)


def test_dict_utf8_roundtrip_and_compression():
    # 10k rows, 5 distinct values -> codes pack at 4 bits
    vocab = [b"prod", b"staging", b"dev", b"test", b"canary"]
    col = [vocab[i % 5] for i in range(10_000)]
    enc = utf8vec.pack_dict_utf8(col)
    assert utf8vec.unpack_dict_utf8(enc) == col
    assert utf8vec.dict_cardinality(enc) == 5
    plain = utf8vec.pack_utf8(col)
    assert len(enc) < len(plain) / 5


def test_label_table_roundtrip_sparse_keys():
    rows = [
        {"job": "api", "instance": "i-1", "_metric_": "heap"},
        {"job": "api", "zone": "us-east", "_metric_": "heap"},
        {"job": "db", "instance": "i-2", "_metric_": "cpu"},
        {},
    ]
    enc = utf8vec.pack_label_table(rows)
    assert utf8vec.unpack_label_table(enc) == rows


def test_label_table_empty_string_values_preserved():
    rows = [{"a": "", "b": "x"}, {"b": ""}, {"a": "y"}]
    enc = utf8vec.pack_label_table(rows)
    assert utf8vec.unpack_label_table(enc) == rows


def test_label_table_large():
    rows = [{"job": f"job{i % 3}", "instance": f"inst-{i}"}
            for i in range(5000)]
    enc = utf8vec.pack_label_table(rows)
    assert utf8vec.unpack_label_table(enc) == rows
