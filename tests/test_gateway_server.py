"""Decoupled gateway server: TCP Influx listener → broker → node ingest →
checkpointed recovery → query (the reference's GatewayServer +
KafkaContainerSink backbone, ref: GatewayServer.scala:58-115,
KafkaContainerSink.scala:24-69)."""
import logging
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.gateway.server import (GatewayServer, KafkaContainerSink,
                                       send_lines)
from filodb_tpu.ingest.filebroker import FileBackedBroker
from filodb_tpu.ingest.stream import create_stream
from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider
from filodb_tpu.query.engine import QueryEngine

START = 1_600_000_000_000
NUM_SHARDS = 4
TOPIC = "timeseries"


def _counter_lines(num_series=24, num_samples=120, start_ms=START):
    """Influx counter lines: one measurement, per-series tags, 10s scrape."""
    rng = np.random.default_rng(3)
    incr = rng.integers(1, 20, size=(num_series, num_samples))
    vals = np.cumsum(incr, axis=1)
    lines = []
    for s in range(num_series):
        tags = f"_ws_=demo,_ns_=App-{s % 4},instance=i{s}"
        for t in range(num_samples):
            ts_ns = (start_ms + t * 10_000) * 1_000_000
            lines.append(f"request_total,{tags} "
                         f"counter={float(vals[s, t])} {ts_ns}")
    return lines


def _consume_into(ms, broker_dir, upto_offset=None):
    """Node side: one filebroker ingestion stream per shard."""
    for shard_num in range(NUM_SHARDS):
        ms.setup("prometheus", shard_num)
        stream = create_stream("filebroker", topic=TOPIC, shard=shard_num,
                               broker_dir=broker_dir)
        batches = stream.batches(-1)
        if upto_offset is not None:
            batches = ((b, o) for b, o in batches if o <= upto_offset)
        ms.ingest_stream("prometheus", shard_num, batches, flush_every=3)
        stream.teardown()
        ms.get_shard("prometheus", shard_num).flush_all_groups()


def _query(ms):
    mapper = ShardMapper(NUM_SHARDS)
    eng = QueryEngine("prometheus", ms, mapper)
    end_s = START // 1000 + 120 * 10
    res = eng.query_range('sum by (_ns_)(rate(request_total[5m]))',
                          START // 1000 + 600, 60, end_s)
    assert res.error is None, res.error
    return {tuple(sorted(k.labels_dict.items())): np.asarray(v)
            for k, _, v in res.series()}


def test_gateway_process_to_broker_to_node_query(tmp_path):
    """Full decoupled pipeline with the gateway as a REAL OS process and
    the TCP socket as the process boundary."""
    broker_dir = str(tmp_path / "broker")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.gateway.server",
         "--broker-dir", broker_dir, "--port", "0",
         "--num-shards", str(NUM_SHARDS), "--topic", TOPIC],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    try:
        line = proc.stdout.readline()
        assert line.startswith("GATEWAY_READY"), line
        port = int(line.strip().split("port=")[1])

        lines = _counter_lines()
        send_lines("127.0.0.1", port, lines)

        # the gateway flushes on connection close; wait for the broker to
        # hold every record
        broker = FileBackedBroker(broker_dir)
        want = len(lines)

        def broker_records():
            from filodb_tpu.core.records import RecordBatch
            return sum(RecordBatch.from_bytes(v).num_records
                       for p in range(NUM_SHARDS)
                       for v in broker.read_all(TOPIC, p))
        deadline = time.time() + 30
        while broker_records() < want and time.time() < deadline:
            time.sleep(0.1)
        assert broker_records() == want
        # per-shard partitioning really happened (spread math spreads the
        # series over multiple partitions)
        assert sum(1 for p in range(NUM_SHARDS)
                   if broker.end_offset(TOPIC, p)) >= 2
    finally:
        proc.terminate()
        proc.wait(timeout=10)

    # node side: consume every shard partition, flush, query
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    _consume_into(ms, broker_dir)
    got = _query(ms)

    # truth: the same lines ingested synchronously (no broker)
    from filodb_tpu.gateway.router import GatewayPipeline
    truth_ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        truth_ms.setup("prometheus", s)
    pipe = GatewayPipeline(truth_ms, "prometheus", ShardMapper(NUM_SHARDS),
                           SpreadProvider(0))
    pipe.ingest_lines(_counter_lines(), offset=1)
    want = _query(truth_ms)
    assert set(got) == set(want) and got
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-6,
                                   equal_nan=True)

    # checkpointed recovery: crash the node store, recover from the
    # flush watermarks, resume the stream, and get identical results
    ms2 = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    for shard_num in range(NUM_SHARDS):
        sh2 = ms2.setup("prometheus", shard_num)
        sh2.recover_index()
        checkpoints = meta.read_checkpoints("prometheus", shard_num)
        resume_from = min(checkpoints.values()) if checkpoints else -1
        if FileBackedBroker(broker_dir).end_offset(TOPIC, shard_num):
            assert resume_from >= 0, \
                f"shard {shard_num} flushed but never checkpointed"
        stream = create_stream("filebroker", topic=TOPIC, shard=shard_num,
                               broker_dir=broker_dir)
        sh2.recover_stream(
            (b, off) for b, off in stream.batches(resume_from))
        stream.teardown()
    got2 = _query(ms2)
    assert set(got2) == set(want)
    for k in want:
        np.testing.assert_allclose(got2[k], want[k], rtol=1e-6,
                                   equal_nan=True)


def test_gateway_server_in_process_histograms(tmp_path):
    """Histogram lines flow through the sink into per-shard frames."""
    broker = FileBackedBroker(str(tmp_path))
    sink = KafkaContainerSink(broker.produce, TOPIC,
                              ShardMapper(NUM_SHARDS), SpreadProvider(0))
    server = GatewayServer(sink, port=0)
    server.start()
    try:
        lines = []
        for s in range(8):
            tags = f"_ws_=demo,_ns_=App-{s % 2},instance=h{s}"
            for t in range(30):
                ts_ns = (START + t * 10_000) * 1_000_000
                c = (t + 1) * (s + 1)
                lines.append(
                    f"http_latency,{tags} "
                    f"0.5={c * 0.3},2={c * 0.7},+Inf={float(c)},"
                    f"sum={c * 1.3},count={float(c)} {ts_ns}")
        send_lines("127.0.0.1", server.port, lines)
        deadline = time.time() + 20
        while sink.stats()["records_out"] < len(lines) \
                and time.time() < deadline:
            time.sleep(0.05)
        stats = sink.stats()
        assert stats["records_out"] == len(lines), stats
        assert stats["drops"] == {}, stats
    finally:
        server.stop()

    from filodb_tpu.core.records import RecordBatch
    frames = [RecordBatch.from_bytes(v) for p in range(NUM_SHARDS)
              for v in broker.read_all(TOPIC, p)]
    assert sum(f.num_records for f in frames) == len(lines)
    assert any(f.schema.name == "prom-histogram" for f in frames)


def test_sink_drop_reasons_accounted_and_logged(tmp_path, caplog):
    """Malformed input increments per-reason counters and emits a warning
    (VERDICT r2: drop accounting must not be silent)."""
    broker = FileBackedBroker(str(tmp_path))
    sink = KafkaContainerSink(broker.produce, TOPIC, ShardMapper(2),
                              SpreadProvider(0))
    lines = [
        "garbage with no fields section_",
        "m,t=1 str=\"not-numeric\" 1600000000000000000",
        "hist,t=1 0.5=1,2=3,sum=4,count=3 1600000000000000000",  # no +Inf
        "ok_metric,t=1 counter=5 1600000000000000000",
    ]
    with caplog.at_level(logging.WARNING, logger="filodb.gateway"):
        n = sink.publish_lines(lines)
    assert n == 1
    drops = sink.stats()["drops"]
    assert drops.get("parse_error") == 1, drops
    assert drops.get("no_numeric_fields") == 1, drops
    assert drops.get("histogram_missing_inf_bucket") == 1, drops
    assert any("dropped lines" in r.message for r in caplog.records)


def test_pipeline_drop_reasons(caplog):
    """The synchronous GatewayPipeline shares the reason accounting."""
    from filodb_tpu.gateway.router import GatewayPipeline
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0)
    pipe = GatewayPipeline(ms, "prometheus", ShardMapper(1),
                           SpreadProvider(0))
    with caplog.at_level(logging.WARNING, logger="filodb.gateway"):
        pipe.ingest_lines(["bad line_", "m,t=1 counter=2 "
                           "1600000000000000000"], offset=1)
    assert pipe.drops.get("parse_error") == 1
    assert any("dropped lines" in r.message for r in caplog.records)
