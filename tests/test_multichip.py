"""Multi-device equivalence suite for the multi-chip fused scan.

Runs on the harness's 8 virtual CPU devices (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8); the `multichip`
marker auto-skips below 2 local devices so tier-1 stays green on
1-device boxes.

What it proves (doc/multichip.md):
  - the full engine with sharded DeviceMirrors + per-device fused
    dispatch returns BIT-IDENTICAL results to the unsharded engine for
    dense, ragged and histogram `sum/max/avg by (rate())` shapes;
  - the MeshExecutor per-device dispatch path matches the general mesh
    path and actually fans out one kernel per device;
  - the partial-only collective merge equals the host-side
    ops/agg.reduce_phase merge;
  - a device-pinned DeviceMirror round-trips the shard partition's
    columns bit-exactly from its assigned device;
  - PackedShards packing is memoized per (shard-set, keys-generation):
    a re-poll after value-only ingest hits the layout memo
    (the ISSUE-6 acceptance gate).
"""
import numpy as np
import pytest

import jax

from filodb_tpu.core.index import Equals
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.ingest.generator import (counter_batch, gauge_batch,
                                         histogram_batch)
from filodb_tpu.ops.timewindow import make_window_ends
from filodb_tpu.parallel.mesh import (MeshExecutor, make_mesh,
                                      merge_device_partials)
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
from filodb_tpu.utils.metrics import registry

from test_query_engine import _mk_engine, START_MS, START_S, NUM_SAMPLES

pytestmark = pytest.mark.multichip

QEND_S = START_S + 3600
STEP_S = 60


def _ragged_counter_batch(num_series, num_samples, seed=7):
    rng = np.random.default_rng(seed)
    cb = counter_batch(num_series, num_samples, start_ms=START_MS, seed=seed)
    v = cb.columns["count"].copy()
    v[rng.random(v.shape) < 0.1] = np.nan
    return RecordBatch(cb.schema, cb.part_keys, cb.part_idx, cb.timestamps,
                       {"count": v}, cb.bucket_les)


def _series_map(res):
    assert res.error is None, res.error
    return {tuple(sorted(k.labels_dict.items())): np.asarray(v)
            for k, _, v in res.series()}


QUERIES = [
    'sum by (_ns_) (rate(request_total{_ws_="demo"}[5m]))',
    'avg by (_ns_) (rate(request_total{_ws_="demo"}[5m]))',
    'max by (_ns_) (rate(request_total{_ws_="demo"}[5m]))',
    'sum by (instance) (increase(request_total{_ws_="demo",_ns_="App-0"}[10m]))',
    'histogram_quantile(0.9, sum by (_ns_) (rate(http_latency{_ws_="demo"}[5m])))',
]


@pytest.mark.parametrize("fused_kernel", [False, True],
                         ids=["general", "fused-kernel"])
def test_engine_sharded_mirrors_bit_parity(monkeypatch, fused_kernel):
    """The engine with per-shard device-pinned mirrors (the sharded
    DeviceMirror mode feeding the per-device dispatch) must return
    bit-identical results to the unsharded engine — same leaves, same
    partial merges, only the executing device differs."""
    def batches():
        return [counter_batch(96, NUM_SAMPLES, start_ms=START_MS),
                _ragged_counter_batch(32, NUM_SAMPLES, seed=11),
                histogram_batch(24, NUM_SAMPLES, num_buckets=8,
                                start_ms=START_MS)]

    if fused_kernel:
        monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    monkeypatch.delenv("FILODB_TPU_FORCE_SHARDED_MIRROR", raising=False)
    eng_flat = _mk_engine(batches(), num_shards=4, spread=2)
    flat = {q: _series_map(eng_flat.query_range(q, START_S + 600, STEP_S,
                                                QEND_S)) for q in QUERIES}

    monkeypatch.setenv("FILODB_TPU_FORCE_SHARDED_MIRROR", "1")
    eng_shard = _mk_engine(batches(), num_shards=4, spread=2)
    sharded = {q: _series_map(eng_shard.query_range(q, START_S + 600,
                                                    STEP_S, QEND_S))
               for q in QUERIES}

    for q in QUERIES:
        assert flat[q].keys() == sharded[q].keys(), q
        for k, want in flat[q].items():
            np.testing.assert_array_equal(sharded[q][k], want,
                                          err_msg=f"{q} {k}")

    # the mirrors really are partitioned: the shards' stores must sit on
    # more than one device
    devs = set()
    for s in range(4):
        sh = eng_shard.source.get_shard("prometheus", s)
        for store in sh.stores.values():
            m = getattr(store, "device_mirror", None)
            if m is not None and m.device is not None:
                devs.add(m.device)
    assert len(devs) >= 2, f"mirrors not spread across devices: {devs}"


def test_mirror_placer_prefers_home_and_respects_hbm_cap():
    from filodb_tpu.core.devicecache import MirrorPlacer
    p = MirrorPlacer()
    devs = jax.local_devices()
    limit = 1000
    d0 = p.assign(0, 600, limit)
    assert d0 == devs[0]
    p.book(d0, 600)
    # shard len(devs) maps home to device 0, which no longer fits ->
    # least-booked device takes it
    d_spill = p.assign(len(devs), 600, limit)
    assert d_spill != d0
    # nothing fits: still places (per-store cap handles degradation)
    for d in devs:
        p.book(d, limit)
    assert p.assign(1, 600, limit) in devs


def test_mirror_shard_partition_roundtrip():
    """A device-pinned mirror must serve back exactly the columns the
    shard partition holds, from its assigned device."""
    from filodb_tpu.core.devicecache import DeviceMirror
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0)
    sh = ms.get_shard("prometheus", 0)
    sh.ingest(counter_batch(16, 64, start_ms=START_MS))
    (schema_name, store), = [(k, v) for k, v in sh.stores.items()]
    dev = jax.local_devices()[min(3, jax.local_device_count() - 1)]
    mirror = DeviceMirror(device=dev, shard_num=0)
    with sh._write_locked("test"):
        assert mirror.ensure_fresh(store)
    snap = mirror.snapshot()
    rows = np.arange(store.num_series)
    got = mirror.gather_cached(rows, snap)
    assert got is not None
    ts_off, cols, vbases, base = got
    # round-trip: device copy == host truth (offsets + absolute values)
    s, t = store.num_series, store.time_used
    want_ts = store.ts[:s, :t]
    counts = store.counts[:s]
    pos = np.arange(t)[None, :]
    got_ts = np.asarray(ts_off, np.int64)
    valid = pos < counts[:, None]
    np.testing.assert_array_equal(got_ts[valid] + base, want_ts[valid])
    name = store.schema.value_column
    got_vals = np.asarray(cols[name], np.float64) \
        + np.asarray(vbases[name], np.float64)[:, None]
    # the mirror reset-corrects counter columns in f64 before rebasing,
    # so the host truth is the corrected column
    from filodb_tpu.ops.counter import host_counter_correct
    want_vals = host_counter_correct(store.cols[name][:s, :t])
    np.testing.assert_allclose(got_vals[valid], want_vals[valid],
                               rtol=1e-6)
    # committed to the assigned device
    for arr in (snap.ts_off, *snap.cols.values()):
        assert set(arr.devices()) == {dev}, \
            f"snapshot array on {arr.devices()}, wanted {dev}"
    from filodb_tpu.core.devicecache import placer
    assert placer.booked(dev) >= 0


def _mk_store4(n_series=64, ragged=False):
    ms = TimeSeriesMemStore()
    mapper = ShardMapper(4)
    for s in range(4):
        ms.setup("prometheus", s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", s, "local"))
    batch = (_ragged_counter_batch(n_series, NUM_SAMPLES)
             if ragged else counter_batch(n_series, NUM_SAMPLES,
                                          start_ms=START_MS))
    shard_of_key = np.asarray([
        mapper.ingestion_shard(pk.shard_key_hash(), pk.partition_hash(), 2)
        for pk in batch.part_keys])
    for s in range(4):
        keep = shard_of_key[batch.part_idx] == s
        if keep.any():
            sub = RecordBatch(batch.schema, batch.part_keys,
                              batch.part_idx[keep], batch.timestamps[keep],
                              {k: v[keep] for k, v in
                               batch.columns.items()},
                              batch.bucket_les)
            ms.get_shard("prometheus", s).ingest(sub)
    return ms


@pytest.mark.parametrize("ragged", [False, True], ids=["dense", "ragged"])
def test_mesh_perdevice_dispatch_parity_and_fanout(monkeypatch, ragged):
    """run_agg's fused route must dispatch the single-chip kernel once
    per mesh device (never inside shard_map) and match the general mesh
    path."""
    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    ms = _mk_store4(ragged=ragged)
    mesh = make_mesh(4, 2, devices=jax.devices()[:8])
    ex = MeshExecutor(ms, "prometheus", mesh)
    filters = [Equals("_metric_", "request_total")]
    packed = ex.lookup_and_pack(filters, START_MS, QEND_S * 1000,
                                by=("_ns_",), fn_name="rate")
    assert packed.shared_ts_row is not None
    assert packed.dense is (not ragged)
    wends = make_window_ends((START_S + 600) * 1000, QEND_S * 1000,
                             STEP_S * 1000)
    k0 = registry.counter("mesh_fused_kernel").value
    d0 = registry.counter("mesh_fused_perdevice_dispatches").value
    fused, labels = ex.run_agg(packed, wends, range_ms=300_000,
                               fn_name="rate", agg_op="sum")
    assert registry.counter("mesh_fused_kernel").value == k0 + 1
    assert registry.counter("mesh_fused_perdevice_dispatches").value \
        == d0 + 8, "per-device dispatch must fan out over all 8 devices"
    # general mesh path over the same pack
    from filodb_tpu.ops import agg as agg_ops
    from filodb_tpu.parallel.mesh import distributed_window_agg
    from jax.sharding import NamedSharding, PartitionSpec as P
    wends_p, W = ex._prep_wends(packed, wends)
    wends_dev = jax.device_put(wends_p, NamedSharding(mesh, P("time")))
    partials = distributed_window_agg(
        mesh, packed.ts_off, packed.values, packed.group_ids, wends_dev,
        range_ms=300_000, fn_name="rate", agg_op="sum",
        num_groups=packed.num_groups, base_ms=packed.base_ms,
        vbase=packed.vbase, precorrected=packed.precorrected,
        dense=packed.dense)
    general = np.asarray(agg_ops.present("sum", partials))[:, :W]
    assert (np.isnan(fused) == np.isnan(general)).all()
    np.testing.assert_allclose(fused, general, rtol=2e-5, atol=1e-4,
                               equal_nan=True)


def test_merge_device_partials_collective_matches_host():
    """The partial-only psum collective and the host-side reduce_phase
    merge are the same reduce — one rides ICI, one rides host memory."""
    mesh = make_mesh(4, 2, devices=jax.devices()[:8])
    rng = np.random.default_rng(0)
    G, Wlp = 16, 128
    parts = {}
    for s in range(4):
        for t in range(2):
            parts[(s, t)] = jax.device_put(
                rng.standard_normal((G, Wlp)).astype(np.float32),
                mesh.devices[s, t])
    via_coll = merge_device_partials(parts, mesh, "sum", collective=True)
    via_host = merge_device_partials(parts, mesh, "sum", collective=False)
    assert via_coll.shape == via_host.shape == (G, 2 * Wlp)
    np.testing.assert_allclose(via_coll, via_host, rtol=1e-6, atol=1e-6)
    for comb, ref in (("min", np.minimum), ("max", np.maximum)):
        got = merge_device_partials(parts, mesh, comb, collective=True)
        want = np.concatenate(
            [ref.reduce([np.asarray(parts[(s, t)], np.float64)
                         for s in range(4)], axis=0) for t in range(2)],
            axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pack_layout_memo_hits_on_repoll():
    """ISSUE-6 acceptance: PackedShards repack is memoized per
    (shard-set, keys-generation) — a re-poll after value-only ingest
    must hit the layout memo (no per-series repack)."""
    ms = _mk_store4()
    mesh = make_mesh(4, 2, devices=jax.devices()[:8])
    ex = MeshExecutor(ms, "prometheus", mesh)
    filters = [Equals("_metric_", "request_total")]
    t0, t1 = START_MS, QEND_S * 1000
    h0 = registry.counter("mesh_pack_memo_hits").value
    ex.lookup_and_pack(filters, t0, t1, by=("_ns_",), fn_name="rate")
    # value-only ingest: same series keys, new samples -> store
    # generations move (pack cache invalidated) but keys stay
    batch = counter_batch(64, 4,
                          start_ms=START_MS + NUM_SAMPLES * 10_000)
    mapper = ShardMapper(4)
    shard_of_key = np.asarray([
        mapper.ingestion_shard(pk.shard_key_hash(), pk.partition_hash(), 2)
        for pk in batch.part_keys])
    for s in range(4):
        keep = shard_of_key[batch.part_idx] == s
        if keep.any():
            sub = RecordBatch(batch.schema, batch.part_keys,
                              batch.part_idx[keep], batch.timestamps[keep],
                              {k: v[keep] for k, v in
                               batch.columns.items()},
                              batch.bucket_les)
            ms.get_shard("prometheus", s).ingest(sub)
    ex.lookup_and_pack(filters, t0, t1 + 40_000, by=("_ns_",),
                       fn_name="rate")
    assert registry.counter("mesh_pack_memo_hits").value > h0, \
        "re-poll after value-only ingest must hit the layout memo"


def test_make_mesh_exposes_shape_and_unused_devices():
    make_mesh(2, 1, devices=jax.devices()[:8])
    assert registry.gauge("mesh_shard_axis").value == 2
    assert registry.gauge("mesh_time_axis").value == 1
    assert registry.gauge("mesh_unused_devices").value == 6
    make_mesh(4, 2, devices=jax.devices()[:8])
    assert registry.gauge("mesh_unused_devices").value == 0
