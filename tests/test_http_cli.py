"""HTTP API + CLI + standalone server tests (models ref:
http/src/test/.../PrometheusApiRouteSpec, cli usage in doc/)."""
import json
import os
import urllib.request

import numpy as np
import pytest

from filodb_tpu.ingest.generator import counter_batch, gauge_batch
from filodb_tpu.standalone import DatasetConfig, FiloServer

START = 1_600_000_020_000
START_S = START // 1000


@pytest.fixture(scope="module")
def server():
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     http_port=0)
    shard = srv.memstore.get_shard("prometheus", 0)
    shard.ingest(gauge_batch(10, 720, start_ms=START))
    shard.ingest(counter_batch(6, 720, start_ms=START))
    srv.start()
    yield srv
    srv.shutdown()


def _get(srv, path, **params):
    import urllib.parse
    url = (f"http://127.0.0.1:{srv.http.port}{path}?"
           + urllib.parse.urlencode(params))
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_health(server):
    st, payload = _get(server, "/__health")
    assert st == 200 and payload["status"] == "healthy"


def test_query_range_http(server):
    st, payload = _get(
        server, "/promql/prometheus/api/v1/query_range",
        query='sum(rate(request_total[5m]))',
        start=START_S + 600, end=START_S + 7200, step=60)
    assert st == 200, payload
    assert payload["status"] == "success"
    result = payload["data"]["result"]
    assert len(result) == 1
    assert len(result[0]["values"]) > 50
    assert float(result[0]["values"][0][1]) > 0


def test_query_instant_http(server):
    st, payload = _get(server, "/promql/prometheus/api/v1/query",
                       query='heap_usage{_ws_="demo"}',
                       time=START_S + 3600)
    assert st == 200 and payload["data"]["resultType"] == "vector"
    assert len(payload["data"]["result"]) == 10


def test_default_dataset_alias(server):
    st, payload = _get(server, "/api/v1/query",
                       query="request_total", time=START_S + 3600)
    assert st == 200
    assert len(payload["data"]["result"]) == 6


def test_labels_and_values(server):
    st, payload = _get(server, "/promql/prometheus/api/v1/labels")
    assert st == 200 and "_ns_" in payload["data"]
    st, payload = _get(server,
                       "/promql/prometheus/api/v1/label/_ws_/values")
    assert st == 200 and payload["data"] == ["demo"]


def test_series_endpoint(server):
    st, payload = _get(server, "/promql/prometheus/api/v1/series",
                       **{"match[]": 'heap_usage{_ws_="demo"}',
                          "start": START_S, "end": START_S + 7200})
    assert st == 200
    assert len(payload["data"]) == 10
    # wire compat (round 5): Prometheus clients expect __name__ here
    assert all(s["__name__"] == "heap_usage" and "_metric_" not in s
               for s in payload["data"])


def test_explain_plan(server):
    st, payload = _get(server, "/promql/prometheus/api/v1/query_range",
                       query='sum(rate(request_total[5m]))',
                       start=START_S, end=START_S + 3600, step=60,
                       explain="true")
    assert st == 200
    tree = "\n".join(payload["data"]["result"])
    assert "ReduceAggregateExec" in tree
    assert "MultiSchemaPartitionsExec" in tree
    assert "PeriodicSamplesMapper" in tree


def test_cluster_status(server):
    st, payload = _get(server, "/cluster/prometheus/status")
    assert st == 200
    assert payload["data"][0]["status"] == "Active"


def test_parse_error_is_400(server):
    import urllib.error
    try:
        _get(server, "/promql/prometheus/api/v1/query_range",
             query="sum(((", start=START_S, end=START_S + 60, step=60)
        assert False, "expected HTTPError"
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert json.loads(e.read())["status"] == "error"


def test_influx_write_roundtrip(server):
    lines = "\n".join(
        f"cpu_temp,_ws_=demo,_ns_=App-0,host=h{i} value={20+i} "
        f"{(START + 1000) * 1_000_000}" for i in range(4))
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.http.port}/influx/write?db=prometheus",
        data=lines.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 204
    st, payload = _get(server, "/promql/prometheus/api/v1/query",
                       query="cpu_temp", time=START_S + 300)
    assert st == 200
    assert len(payload["data"]["result"]) == 4


def test_loglevel_admin(server):
    import logging
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.http.port}/admin/loglevel/filodb.test",
        data=b"DEBUG", method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 200
    assert logging.getLogger("filodb.test").level == logging.DEBUG


# ------------------------------------------------------------------- CLI


def test_cli_roundtrip(tmp_path):
    from filodb_tpu.cli import main
    data_dir = str(tmp_path / "data")
    assert main(["init", "--data-dir", data_dir]) == 0

    csv = tmp_path / "in.csv"
    rows = ["metric,tags,timestamp,value"]
    for i in range(50):
        rows.append(f"cpu_load,host=h{i % 5},{START + i * 10_000},{i * 1.5}")
    csv.write_text("\n".join(rows))
    assert main(["importcsv", "--data-dir", data_dir,
                 "--file", str(csv)]) == 0

    assert main(["list", "--data-dir", data_dir]) == 0
    assert main(["indexnames", "--data-dir", data_dir]) == 0
    assert main(["indexvalues", "--data-dir", data_dir,
                 "--label", "host"]) == 0
    assert main(["validateSchemas"]) == 0
    assert main(["decodechunks", "--data-dir", data_dir]) == 0
    assert main(["query", "--data-dir", data_dir,
                 "--promql", "cpu_load",
                 "--start", str(START_S), "--end", str(START_S + 600),
                 "--step", "60"]) == 0


def test_cli_query_output(tmp_path, capsys):
    from filodb_tpu.cli import main
    data_dir = str(tmp_path / "data")
    main(["init", "--data-dir", data_dir])
    csv = tmp_path / "in.csv"
    rows = ["metric,tags,timestamp,value"]
    for i in range(30):
        rows.append(f"mem_used,app=web,{START + i * 10_000},{100 + i}")
    csv.write_text("\n".join(rows))
    main(["importcsv", "--data-dir", data_dir, "--file", str(csv)])
    capsys.readouterr()
    rc = main(["query", "--data-dir", data_dir, "--raw",
               "--promql", 'mem_used{app="web"}',
               "--start", str(START_S), "--end", str(START_S + 300),
               "--step", "60"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["status"] == "success"
    assert payload["data"]["result"][0]["metric"]["app"] == "web"


def test_cli_partkey_and_decodevector(tmp_path, capsys):
    from filodb_tpu.cli import main
    # partkey: filter -> bytes + routing (promFilterToPartKeyBR analogue)
    assert main(["partkey", 'cpu_load{_ws_="demo",host="h1"}',
                 "--num-shards", "16", "--spread", "1"]) == 0
    out = capsys.readouterr().out
    assert "partitionHash" in out and "ingestionShard" in out
    assert "cpu_load" in out

    # decodevector: persisted chunk sample dump (decodeVector analogue)
    data_dir = str(tmp_path / "data")
    main(["init", "--data-dir", data_dir])
    csv = tmp_path / "in.csv"
    rows = ["metric,tags,timestamp,value"]
    for i in range(30):
        rows.append(f"cpu_load,host=h{i % 3},{START + i * 10_000},{i * 1.5}")
    csv.write_text("\n".join(rows))
    assert main(["importcsv", "--data-dir", data_dir,
                 "--file", str(csv)]) == 0
    assert main(["decodevector", "--data-dir", data_dir,
                 "--rows", "2", "--limit", "2"]) == 0
    out = capsys.readouterr().out
    assert "chunk=" in out and "value=" in out


def test_cli_partkey_equality_only(capsys):
    from filodb_tpu.cli import main
    # NotEquals must not be treated as a pinned label
    assert main(["partkey", 'cpu{_ws_="demo",host!="h1"}']) == 0
    out = capsys.readouterr().out
    assert "host" not in out.split("partKey")[1].splitlines()[0]
    # a metric pinned only by != is rejected
    assert main(["partkey", '{__name__!="x",_ws_="demo"}']) == 1


def test_cli_chunkinfos_and_decodechunkinfo(tmp_path, capsys):
    """SelectChunkInfos debug plan via CLI + hex chunk-frame decoding
    (ref: SelectChunkInfosExec.scala, CliMain decodeChunkInfo)."""
    import json

    from filodb_tpu.cli import main
    data_dir = str(tmp_path / "data")
    main(["init", "--data-dir", data_dir])
    csv = tmp_path / "in.csv"
    rows = ["metric,tags,timestamp,value"]
    for i in range(60):
        rows.append(f"cpu_load,host=h{i % 3},{START + i * 10_000},{i * 1.5}")
    csv.write_text("\n".join(rows))
    assert main(["importcsv", "--data-dir", data_dir,
                 "--file", str(csv)]) == 0
    capsys.readouterr()

    assert main(["chunkinfos", "--data-dir", data_dir,
                 'cpu_load{host="h1"}']) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "chunkinfos printed nothing"
    infos = [json.loads(line) for line in out]
    assert all(i["_metric_"] == "cpu_load" and i["host"] == "h1"
               for i in infos)
    assert any(i["tier"] in ("resident", "persisted") for i in infos)
    assert all(i["numRows"] > 0 and i["endTime"] >= i["startTime"]
               for i in infos)
    assert any("ts-dd" in str(i["encodings"].values()) or i["encodings"]
               for i in infos)

    # decodechunkinfo: hex frame -> metadata json
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.memory.chunks import encode_chunkset
    from filodb_tpu.persist.localstore import _encode_chunkset_frame
    import numpy as np
    ts = START + np.arange(20, dtype=np.int64) * 10_000
    cs = encode_chunkset(ts, {"value": np.arange(20) * 2.0},
                         {"value": "double"}, START)
    frame = _encode_chunkset_frame(
        PartKey.make("cpu_load", {"host": "h1"}), "gauge", cs)
    assert main(["decodechunkinfo", frame.hex()]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["partKey"]["metric"] == "cpu_load"
    assert doc["numRows"] == 20 and doc["schema"] == "gauge"
    assert doc["encodings"]


def test_query_range_batch_http(server):
    """Dashboard batch endpoint: one POST answers every panel, each
    payload matching its individual query_range response."""
    queries = ['sum(rate(request_total[5m])) by (_ns_)',
               'avg(rate(request_total[5m])) by (dc)',
               'bad{{{']
    body = json.dumps({"queries": queries, "start": START_S + 600,
                       "end": START_S + 7200, "step": 60}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.http.port}"
        f"/promql/prometheus/api/v1/query_range_batch",
        data=body, headers={"Content-Type": "application/json"},
        method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        st, payload = r.status, json.loads(r.read())
    assert st == 200 and payload["status"] == "success"
    results = payload["results"]
    assert len(results) == 3
    assert results[2]["status"] == "error"
    for q, got in zip(queries[:2], results[:2]):
        _, want = _get(server, "/promql/prometheus/api/v1/query_range",
                       query=q, start=START_S + 600, end=START_S + 7200,
                       step=60)
        assert got["status"] == "success"
        assert got["data"]["result"] == want["data"]["result"], q


def test_cli_querybatch(tmp_path, capsys):
    from filodb_tpu.cli import main
    data_dir = str(tmp_path / "data")
    main(["init", "--data-dir", data_dir])
    csv = tmp_path / "in.csv"
    rows = ["metric,tags,timestamp,value"]
    for i in range(30):
        rows.append(f"mem_used,app=web,{START + i * 10_000},{100 + i}")
        rows.append(f"mem_used,app=db,{START + i * 10_000},{200 + i}")
    csv.write_text("\n".join(rows))
    main(["importcsv", "--data-dir", data_dir, "--file", str(csv)])
    capsys.readouterr()
    rc = main(["querybatch", "--data-dir", data_dir, "--raw",
               "--promql", 'sum(mem_used) by (app)',
               "--promql", 'avg(mem_used) by (app)',
               "--start", str(START_S), "--end", str(START_S + 300),
               "--step", "60"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["status"] == "success"
    assert len(payload["results"]) == 2
    for r in payload["results"]:
        assert r["status"] == "success"
        apps = {m["metric"]["app"] for m in r["data"]["result"]}
        assert apps == {"web", "db"}


def test_http_micro_batching_coalesces_panels(monkeypatch):
    """query.batch_window_ms > 0: concurrent query_range HTTP requests
    (one per dashboard panel, as Grafana sends them) coalesce into
    merged kernel dispatches server-side, responses unchanged."""
    import threading

    from filodb_tpu.config import settings
    from filodb_tpu.utils.metrics import registry

    monkeypatch.setenv("FILODB_TPU_FUSED_INTERPRET", "1")
    monkeypatch.setattr(settings().query, "batch_window_ms", 250.0)
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     http_port=0)
    srv.memstore.get_shard("prometheus", 0).ingest(
        counter_batch(30, 240, start_ms=START))
    srv.start()
    try:
        queries = ['sum(rate(request_total[5m])) by (_ns_)',
                   'avg(rate(request_total[5m])) by (dc)',
                   'sum(rate(request_total[5m])) by (dc)']
        args = {"start": START_S + 600, "end": START_S + 2390, "step": 60}
        # warm the mirror (sequential; not coalesced with the batch below)
        _get(srv, "/promql/prometheus/api/v1/query_range",
             query=queries[0], **args)
        want = [_get(srv, "/promql/prometheus/api/v1/query_range",
                     query=q, **args)[1] for q in queries]
        # the sequential `want` round populated the frontend's result
        # cache, which would serve the concurrent round without ever
        # reaching the coalescer — this test is about FIRST-CONTACT
        # coalescing of distinct panels, so start it cold
        cache = srv.api.frontends["prometheus"].cache
        if cache is not None:
            cache.clear()
        merged0 = registry.counter("fused_batch_merged_panels").value
        got = {}

        def call(q):
            got[q] = _get(srv, "/promql/prometheus/api/v1/query_range",
                          query=q, **args)

        threads = [threading.Thread(target=call, args=(q,))
                   for q in queries]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert registry.counter("fused_batch_merged_panels").value \
            - merged0 >= 2, "HTTP requests did not coalesce"
        for q, w in zip(queries, want):
            st, payload = got[q]
            assert st == 200
            assert payload["data"]["result"] == w["data"]["result"], q
    finally:
        srv.shutdown()


def test_injected_config_controls_batch_window():
    """The coalescing window must follow the INJECTED FilodbSettings, not
    the global singleton (review r4)."""
    from filodb_tpu.config import FilodbSettings
    cfg = FilodbSettings()
    cfg.query.batch_window_ms = 123.0
    srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                     http_port=0, config=cfg)
    try:
        co = srv.api.coalescers["prometheus"]
        assert co.window_s == pytest.approx(0.123)
    finally:
        srv.shutdown()
