"""PromQL parser conformance (models ref: prometheus/src/test/.../parse/
ParserSpec.scala)."""
import pytest

from filodb_tpu.core.index import Equals, EqualsRegex, NotEquals
from filodb_tpu.promql import parse_query, query_range_to_logical_plan, TimeStepParams
from filodb_tpu.promql.lexer import ParseError, duration_to_ms, tokenize
from filodb_tpu.promql import ast as A
from filodb_tpu.query import logical as lp

T = TimeStepParams(1000, 10, 2000)


def plan(q):
    return query_range_to_logical_plan(q, T)


# ------------------------------------------------------------------- lexer

def test_durations():
    assert duration_to_ms("5m") == 300_000
    assert duration_to_ms("1h30m") == 5_400_000
    assert duration_to_ms("90s") == 90_000
    assert duration_to_ms("1d") == 86_400_000


def test_tokenize_basic():
    kinds = [t.kind for t in tokenize('sum(rate(foo{a="b"}[5m]))')]
    assert "DURATION" in kinds and "STRING" in kinds


# ------------------------------------------------------------------ parser

def test_simple_selector():
    e = parse_query('http_requests_total{job="api", instance!="i1"}')
    assert isinstance(e, A.VectorSelector)
    assert e.metric == "http_requests_total"
    assert e.matchers[0].op == "=" and e.matchers[1].op == "!="


def test_selector_to_plan():
    p = plan('foo{_ws_="demo",_ns_="app"}')
    assert isinstance(p, lp.PeriodicSeries)
    f = p.raw_series.filters
    assert Equals("_metric_", "foo") in f
    assert Equals("_ws_", "demo") in f
    assert p.start_ms == 1_000_000 and p.end_ms == 2_000_000


def test_rate_window():
    p = plan('rate(foo[5m])')
    assert isinstance(p, lp.PeriodicSeriesWithWindowing)
    assert p.function == "rate" and p.window_ms == 300_000
    # chunk scan starts window earlier
    assert p.series.range_selector.from_ms == 1_000_000 - 300_000


def test_aggregate_by():
    p = plan('sum by (job) (rate(foo[1m]))')
    assert isinstance(p, lp.Aggregate)
    assert p.operator == "sum" and p.by == ("job",)
    p2 = plan('sum(rate(foo[1m])) by (job)')
    assert p2.by == ("job",)
    p3 = plan('sum without (instance) (foo)')
    assert p3.without == ("instance",)


def test_topk_quantile_params():
    p = plan('topk(5, foo)')
    assert p.operator == "topk" and p.params == (5.0,)
    p = plan('quantile(0.9, foo)')
    assert p.params == (0.9,)
    p = plan('count_values("version", foo)')
    assert p.params == ("version",)


def test_binary_join_precedence():
    p = plan('a + b * c')
    assert isinstance(p, lp.BinaryJoin) and p.operator == "+"
    assert isinstance(p.rhs, lp.BinaryJoin) and p.rhs.operator == "*"


def test_power_right_assoc():
    p = plan('2 ^ 3 ^ 2')
    assert isinstance(p, lp.ScalarBinaryOperation)
    assert isinstance(p.rhs, lp.ScalarBinaryOperation)


def test_scalar_vector_op():
    p = plan('foo * 2')
    assert isinstance(p, lp.ScalarVectorBinaryOperation)
    assert not p.scalar_is_lhs
    p = plan('2 < foo')
    assert p.scalar_is_lhs


def test_bool_modifier():
    p = plan('foo > bool 2')
    assert isinstance(p, lp.ScalarVectorBinaryOperation)
    assert p.operator == ">_bool"


def test_on_group_left():
    p = plan('a * on (job) group_left (extra) b')
    assert isinstance(p, lp.BinaryJoin)
    assert p.on == ("job",) and p.cardinality == "ManyToOne"
    assert p.include == ("extra",)


def test_set_operators():
    p = plan('a and b')
    assert isinstance(p, lp.BinaryJoin) and p.operator == "and"
    p = plan('a unless on (x) b')
    assert p.operator == "unless" and p.on == ("x",)


def test_instant_functions():
    p = plan('abs(foo)')
    assert isinstance(p, lp.ApplyInstantFunction) and p.function == "abs"
    p = plan('clamp_max(foo, 10)')
    assert p.function_args == (10.0,)
    p = plan('histogram_quantile(0.9, sum(rate(lat_bucket[5m])))')
    assert p.function == "histogram_quantile"
    assert isinstance(p.vectors, lp.Aggregate)


def test_offset():
    p = plan('rate(foo[5m] offset 10m)')
    assert p.offset_ms == 600_000
    p = plan('foo offset 1h')
    assert p.offset_ms == 3_600_000


def test_subquery():
    p = plan('max_over_time(rate(foo[1m])[10m:30s])')
    assert isinstance(p, lp.SubqueryWithWindowing)
    assert p.function == "max_over_time"
    assert p.subquery_window_ms == 600_000 and p.subquery_step_ms == 30_000
    assert isinstance(p.inner, lp.PeriodicSeriesWithWindowing)


def test_scalar_functions():
    p = plan('scalar(foo)')
    assert isinstance(p, lp.ScalarVaryingDoublePlan)
    p = plan('vector(1)')
    assert isinstance(p, lp.VectorPlan)
    p = plan('time()')
    assert isinstance(p, lp.ScalarTimeBasedPlan)


def test_absent_and_sort():
    p = plan('absent(foo{job="x"})')
    assert isinstance(p, lp.ApplyAbsentFunction)
    assert Equals("job", "x") in p.filters
    p = plan('sort_desc(foo)')
    assert isinstance(p, lp.ApplySortFunction) and p.function == "sort_desc"


def test_label_replace():
    p = plan('label_replace(foo, "dst", "$1", "src", "(.*)")')
    assert isinstance(p, lp.ApplyMiscellaneousFunction)
    assert p.string_args == ("dst", "$1", "src", "(.*)")


def test_column_selector_extension():
    p = plan('foo::sum{_ws_="w"}')
    assert isinstance(p, lp.PeriodicSeries)
    assert p.raw_series.columns == ("sum",)
    assert Equals("_metric_", "foo") in p.raw_series.filters


def test_regex_matcher():
    p = plan('foo{job=~"a.*", x!~"b"}')
    f = p.raw_series.filters
    assert EqualsRegex("job", "a.*") in f


def test_unary_minus():
    p = plan('-foo')
    assert isinstance(p, lp.ScalarVectorBinaryOperation)
    assert p.scalar_is_lhs and p.operator == "-"
    p = plan('-(3)')
    assert isinstance(p, lp.ScalarFixedDoublePlan) and p.scalar == -3.0


def test_parse_errors():
    with pytest.raises(ParseError):
        parse_query('foo{')
    with pytest.raises(ParseError):
        parse_query('rate(foo)')  # missing range -> conversion error
        query_range_to_logical_plan('rate(foo)', T)
    with pytest.raises(ParseError):
        query_range_to_logical_plan('rate(foo)', T)
    with pytest.raises(ParseError):
        parse_query('sum(foo')


def test_nested_full_query():
    q = ('histogram_quantile(0.75, sum(rate(http_req_latency_bucket'
         '{_ws_="demo",_ns_="App-0"}[5m])) by (le))')
    p = plan(q)
    assert isinstance(p, lp.ApplyInstantFunction)
    agg = p.vectors
    assert agg.by == ("le",)


def test_unary_minus_power_precedence():
    # Prometheus: '^' binds tighter than unary minus: -2^2 == -(2^2)
    import filodb_tpu.promql.ast as A
    e = parse_query("-2^2")
    assert isinstance(e, A.Unary) and isinstance(e.expr, A.BinaryExpr)
    assert e.expr.op == "^"
    e2 = parse_query("2^-3")          # RHS of ^ may be unary
    assert isinstance(e2, A.BinaryExpr) and isinstance(e2.rhs, A.Unary)
    e3 = parse_query("2^3^2")         # right-assoc
    assert isinstance(e3.rhs, A.BinaryExpr) and e3.rhs.op == "^"


def test_subquery_at_modifier_pins_grid():
    """@ on a top-level subquery pins its evaluation grid via a
    non-repeating ApplyAtTimestamp wrapper (the result is a matrix,
    meaningful in instant queries)."""
    from filodb_tpu.query import logical as lp
    plan = query_range_to_logical_plan(
        "rate(foo[5m])[30m:1m] @ 1600000000", T)
    assert isinstance(plan, lp.ApplyAtTimestamp) and not plan.repeat
    assert plan.inner.start_ms == plan.inner.end_ms == 1_600_000_000_000


def test_absent_over_time_unparse_roundtrip():
    """absent_over_time plans as ApplyAbsentFunction(present_over_time);
    the remote-dispatch unparse must render the SURFACE form so a remote
    re-parse keeps the selector's matcher labels (review r4: the naive
    absent(present_over_time(...)) rendering re-parsed with filters=())."""
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          query_range_to_logical_plan)
    from filodb_tpu.query import planutils as pu
    tsp = TimeStepParams(1000, 60, 2000)
    plan = query_range_to_logical_plan(
        'absent_over_time(gappy{l="g"}[10m])', tsp)
    q = pu.unparse(plan)
    assert q == 'absent_over_time(gappy{l="g"}[10m])'
    plan2 = query_range_to_logical_plan(q, tsp)
    assert plan2.filters == plan.filters and plan.filters
    sq = query_range_to_logical_plan(
        'absent_over_time(metricx[10m:1m])', tsp)
    sq2 = query_range_to_logical_plan(pu.unparse(sq), tsp)
    assert type(sq2).__name__ == "ApplyAbsentFunction"
