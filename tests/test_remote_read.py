"""Prometheus remote-read endpoint: snappy codec, prompb wire format, and
the /api/v1/read round trip (ref: PrometheusApiRoute.scala:37-62,
remote/RemoteStorage.java)."""
import numpy as np
import pytest

from filodb_tpu.http import remotepb
from filodb_tpu.utils import snappy

START = 1_600_000_000_000


# ------------------------------------------------------------------ snappy

def test_snappy_roundtrip_various_sizes():
    rng = np.random.default_rng(3)
    for n in (0, 1, 59, 60, 61, 1000, 70_000):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert snappy.decompress(snappy.compress(data)) == data


def test_snappy_compresses_repetitive_data():
    data = b"abcdefgh" * 4096
    comp = snappy.compress(data)
    assert snappy.decompress(comp) == data
    assert len(comp) < len(data) // 4       # back-references actually used


def test_snappy_decodes_foreign_copy_ops():
    """Hand-built streams using all three copy encodings, as a real snappy
    writer would emit them."""
    # "abcd" literal + 1-byte-offset copy (len 4, offset 4) => "abcdabcd"
    blob = bytes([8]) + bytes([(4 - 1) << 2]) + b"abcd" \
        + bytes([(0 << 5) | ((4 - 4) << 2) | 1, 4])
    assert snappy.decompress(blob) == b"abcdabcd"
    # overlapping RLE copy: "ab" + copy(offset=2, len=6) => "abababab"
    blob = bytes([8]) + bytes([(2 - 1) << 2]) + b"ab" \
        + bytes([((6 - 1) << 2) | 2, 2, 0])
    assert snappy.decompress(blob) == b"abababab"
    # 4-byte-offset copy
    blob = bytes([8]) + bytes([(4 - 1) << 2]) + b"wxyz" \
        + bytes([((4 - 1) << 2) | 3, 4, 0, 0, 0])
    assert snappy.decompress(blob) == b"wxyzwxyz"


def test_snappy_rejects_malformed():
    with pytest.raises(ValueError):
        snappy.decompress(b"")
    with pytest.raises(ValueError):          # copy before any output
        snappy.decompress(bytes([4]) + bytes([(4 - 1) << 2 | 1, 1]))
    with pytest.raises(ValueError):          # declared length mismatch
        snappy.decompress(bytes([99]) + bytes([(4 - 1) << 2]) + b"abcd")


# ------------------------------------------------------------------ prompb

def test_prompb_request_roundtrip():
    req = [remotepb.PromQuery(START, START + 60_000, [
        remotepb.LabelMatcher(remotepb.EQ, "__name__", "request_total"),
        remotepb.LabelMatcher(remotepb.RE, "_ns_", "App-.*"),
        remotepb.LabelMatcher(remotepb.NEQ, "dc", "DC1"),
    ])]
    decoded = remotepb.decode_read_request(remotepb.encode_read_request(req))
    assert decoded == req


def test_prompb_response_roundtrip():
    ts = remotepb.PromTimeSeries(
        labels=[("__name__", "m"), ("app", "a")],
        samples=[(1.5, START), (float("nan"), START + 1000), (-2.25, START + 2000)])
    out = remotepb.decode_read_response(
        remotepb.encode_read_response([[ts]]))
    assert len(out) == 1 and len(out[0]) == 1
    got = out[0][0]
    assert got.labels == ts.labels
    assert got.samples[0] == (1.5, START)
    assert np.isnan(got.samples[1][0]) and got.samples[1][1] == START + 1000
    assert got.samples[2] == (-2.25, START + 2000)


def test_prompb_negative_int64():
    req = [remotepb.PromQuery(-5, -1, [])]
    assert remotepb.decode_read_request(
        remotepb.encode_read_request(req)) == req


# ----------------------------------------------------------------- endpoint

@pytest.fixture()
def api():
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.http.routes import PromHttpApi
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.query.engine import QueryEngine
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0)
    batch = counter_batch(12, 50, start_ms=START)
    ms.ingest("prometheus", 0, batch, offset=1)
    eng = QueryEngine("prometheus", ms)
    return PromHttpApi({"prometheus": eng}), batch


def _read(api_obj, queries):
    body = snappy.compress(remotepb.encode_read_request(queries))
    status, payload = api_obj.handle("POST", "/api/v1/read", {}, body)
    assert status == 200, payload
    assert isinstance(payload, bytes)
    return remotepb.decode_read_response(snappy.decompress(payload))


def test_remote_read_returns_raw_samples(api):
    api_obj, batch = api
    q = remotepb.PromQuery(START, START + 500_000, [
        remotepb.LabelMatcher(remotepb.EQ, "__name__", "request_total"),
        remotepb.LabelMatcher(remotepb.EQ, "_ns_", "App-3"),
    ])
    results = _read(api_obj, [q])
    assert len(results) == 1
    series = results[0]
    assert series, "no series returned"
    for ts in series:
        labels = dict(ts.labels)
        assert labels["__name__"] == "request_total"
        assert labels["_ns_"] == "App-3"
        # locate the source series in the batch and compare raw samples
        target = None
        for i, pk in enumerate(batch.part_keys):
            pkl = dict(pk.tags)
            if (pk.metric == "request_total"
                    and all(pkl.get(k) == v for k, v in labels.items()
                            if k != "__name__")):
                target = i
                break
        assert target is not None, labels
        sel = batch.part_idx == target
        want_ts = batch.timestamps[sel]
        want_v = batch.columns["count"][sel]
        got_ts = np.array([t for _, t in ts.samples])
        got_v = np.array([v for v, _ in ts.samples])
        np.testing.assert_array_equal(got_ts, want_ts)
        np.testing.assert_allclose(got_v, want_v, rtol=1e-12)


def test_remote_read_time_range_clipping(api):
    api_obj, batch = api
    lo, hi = START + 100_000, START + 200_000
    q = remotepb.PromQuery(lo, hi, [
        remotepb.LabelMatcher(remotepb.EQ, "__name__", "request_total")])
    results = _read(api_obj, [q])
    assert results[0]
    for ts in results[0]:
        for _, t in ts.samples:
            assert lo <= t <= hi


def test_remote_read_regex_and_neq_matchers(api):
    api_obj, _ = api
    q = remotepb.PromQuery(START, START + 500_000, [
        remotepb.LabelMatcher(remotepb.EQ, "__name__", "request_total"),
        remotepb.LabelMatcher(remotepb.RE, "_ns_", "App-[12]"),
        remotepb.LabelMatcher(remotepb.NEQ, "_ns_", "App-2"),
    ])
    results = _read(api_obj, [q])
    ns = {dict(ts.labels)["_ns_"] for ts in results[0]}
    assert ns == {"App-1"}


def test_remote_read_multiple_queries(api):
    api_obj, _ = api
    qs = [remotepb.PromQuery(START, START + 500_000, [
              remotepb.LabelMatcher(remotepb.EQ, "__name__", "request_total"),
              remotepb.LabelMatcher(remotepb.EQ, "_ns_", f"App-{i}")])
          for i in (1, 2)]
    results = _read(api_obj, qs)
    assert len(results) == 2
    assert all(r for r in results)


def test_remote_read_bad_payload_is_400(api):
    api_obj, _ = api
    status, payload = api_obj.handle("POST", "/api/v1/read", {}, b"not snappy")
    assert status == 400
