"""Quantile sketch partials (ref: QuantileRowAggregator.scala:87 t-digest).

Exact when a (group, window) cell holds <= K samples; bounded-error and
mergeable beyond that; O(groups) wire size regardless of series count.
"""
import numpy as np
import pytest

from filodb_tpu.ops.sketch import (K_DEFAULT, merge_sketches, sketch_quantile,
                                   sketch_from_values)


def _prom_quantile(xs, q):
    xs = np.asarray(xs, float)
    xs = xs[~np.isnan(xs)]
    if xs.size == 0:
        return np.nan
    return np.quantile(xs, q, method="linear")


@pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.9, 1.0])
def test_exact_under_k_samples(q):
    rng = np.random.default_rng(1)
    vals = rng.normal(10, 4, size=(40, 6))
    vals[rng.random(vals.shape) < 0.15] = np.nan
    gids = (np.arange(40) % 3).astype(np.int64)
    sk = sketch_from_values(vals, gids, 3)
    out = sketch_quantile(sk, q)
    for g in range(3):
        for w in range(6):
            want = _prom_quantile(vals[gids == g, w], q)
            got = out[g, w]
            if np.isnan(want):
                assert np.isnan(got)
            else:
                np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_merge_exact_under_k_total():
    rng = np.random.default_rng(2)
    a = rng.normal(0, 1, size=(20, 4))
    b = rng.normal(5, 2, size=(25, 4))
    gids_a = np.zeros(20, dtype=np.int64)
    gids_b = np.zeros(25, dtype=np.int64)
    sa = sketch_from_values(a, gids_a, 1)
    sb = sketch_from_values(b, gids_b, 1)
    merged = merge_sketches(np.concatenate([sa, sb], axis=2))
    out = sketch_quantile(merged, 0.75)
    want = [_prom_quantile(np.concatenate([a[:, w], b[:, w]]), 0.75)
            for w in range(4)]
    np.testing.assert_allclose(out[0], want, rtol=1e-12)


def test_bounded_error_at_scale():
    rng = np.random.default_rng(3)
    N = 20_000
    vals = rng.normal(100, 15, size=(N, 3))
    gids = np.zeros(N, dtype=np.int64)
    sk = sketch_from_values(vals, gids, 1)
    assert sk.shape == (1, 3, K_DEFAULT, 2)
    for q in (0.1, 0.5, 0.9):
        got = sketch_quantile(sk, q)[0]
        want = np.quantile(vals, q, axis=0)
        # equal-depth bins: rank error <= 1/K of the population
        np.testing.assert_allclose(got, want, rtol=0.02)


def test_merge_bounded_error_many_shards():
    rng = np.random.default_rng(4)
    per_shard = [rng.exponential(7.0, size=(5_000, 2)) for _ in range(8)]
    sketches = [sketch_from_values(v, np.zeros(len(v), np.int64), 1)
                for v in per_shard]
    merged = merge_sketches(np.concatenate(sketches, axis=2))
    assert merged.shape[2] == K_DEFAULT
    allv = np.concatenate(per_shard, axis=0)
    for q in (0.5, 0.95):
        got = sketch_quantile(merged, q)[0]
        want = np.quantile(allv, q, axis=0)
        np.testing.assert_allclose(got, want, rtol=0.05)


def test_high_quantile_with_dead_centroids():
    """Weight>1 centroids + padded weight-0 slots (the post-merge shape)
    must not turn q=1.0 / q=0.9 into NaN."""
    sk = np.zeros((1, 1, 3, 2))
    sk[0, 0, :, 0] = [10.0, 20.0, np.nan]
    sk[0, 0, :, 1] = [5.0, 5.0, 0.0]
    assert sketch_quantile(sk, 1.0)[0, 0] == 20.0
    assert 10.0 <= sketch_quantile(sk, 0.9)[0, 0] <= 20.0
    # merge of a 65-sample shard with a 1-sample shard
    rng = np.random.default_rng(7)
    big = sketch_from_values(rng.normal(0, 1, size=(65, 1)),
                             np.zeros(65, np.int64), 1)
    small = sketch_from_values(np.full((1, 1), 99.0), np.zeros(1, np.int64), 1)
    merged = merge_sketches(np.concatenate([big, small], axis=2))
    assert np.isfinite(sketch_quantile(merged, 1.0)[0, 0])


def test_out_of_range_q():
    vals = np.ones((5, 2))
    sk = sketch_from_values(vals, np.zeros(5, np.int64), 1)
    assert (sketch_quantile(sk, 1.5) == np.inf).all()
    assert (sketch_quantile(sk, -0.5) == -np.inf).all()


def test_cross_shard_quantile_wire_cost_is_o_groups():
    """The reduce input/output for quantile() must be sketch-sized, not
    candidate-row-sized."""
    from filodb_tpu.query.exec import (AggregateMapReduce, ResultBlock,
                                       reduce_partials)
    from filodb_tpu.query.rangevector import (QueryContext, QueryStats,
                                              RangeVectorKey)
    S, W = 500, 7
    rng = np.random.default_rng(5)
    wends = np.arange(W, dtype=np.int64)
    partials = []
    for shard in range(3):
        keys = [RangeVectorKey.make({"_ns_": f"App-{i % 2}",
                                     "instance": f"s{shard}-{i}"})
                for i in range(S)]
        block = ResultBlock(keys, wends, rng.normal(0, 1, size=(S, W)))
        p = AggregateMapReduce("quantile", params=(0.9,), by=("_ns_",)).apply(
            block, QueryContext(), QueryStats())
        assert p.sketch is not None and p.cand_vals is None
        assert p.sketch.shape == (2, W, K_DEFAULT, 2)   # groups, not series
        partials.append(p)
    merged = reduce_partials(partials)
    assert merged.sketch.shape == (2, W, K_DEFAULT, 2)
    from filodb_tpu.query.exec import present_partial
    out = present_partial(merged)
    assert out.values.shape == (2, W)
    # sanity: close to the exact quantile over all 1500 series per group
    assert np.isfinite(out.values).all()
