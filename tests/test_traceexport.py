"""Zipkin trace-exporter unit coverage (utils/traceexport.py) — the
ISSUE-10 satellite: batch shape, drain-on-flush, and the sink-failure
path never wedging query serving (previously only the happy file/HTTP
paths were exercised, in tests/test_tracing.py)."""
import queue
import threading
import time

import pytest

from filodb_tpu.utils.metrics import (collector, registry, span,
                                      trace_context)
from filodb_tpu.utils.traceexport import TraceExporter, _zipkin_span


def _event(i=0):
    return {"span": f"exec.{i}", "dur_s": 0.002,
            "end_unix_s": time.time(), "node": "n1", "shard": str(i)}


# ---------------------------------------------------------------- batching

def test_flush_ships_in_batch_sized_chunks():
    """One _flush drains the WHOLE queue but ships it in `batch`-sized
    POSTs (Zipkin collectors reject oversized bodies; the batch bound is
    the contract)."""
    shipped = []
    exp = TraceExporter("http://unused.invalid/api/v2/spans", batch=16)
    exp._ship = lambda spans: shipped.append(list(spans))
    for i in range(40):
        exp.sink("a" * 32, _event(i))
    exp._flush()
    assert [len(b) for b in shipped] == [16, 16, 8]
    # every span arrived exactly once, order preserved
    names = [s["name"] for b in shipped for s in b]
    assert names == [f"exec.{i}" for i in range(40)]


def test_zipkin_span_shape():
    """The v2 span dict: 32-hex traceId (uuid dashes stripped; non-uuid
    ids hashed), microsecond duration floored at 1, tags carry the
    event's extra fields but not the structural ones."""
    ev = _event(3)
    sp = _zipkin_span("11111111-2222-3333-4444-555555555555", ev)
    assert sp["traceId"] == "11111111222233334444555555555555"
    assert sp["name"] == "exec.3"
    assert sp["duration"] == 2000
    assert sp["localEndpoint"]["serviceName"] == "n1"
    assert sp["tags"] == {"shard": "3"}
    # a non-hex trace id still produces a valid 32-hex id
    weird = _zipkin_span("not-a-uuid!", _event())
    assert len(weird["traceId"]) == 32
    assert all(c in "0123456789abcdef" for c in weird["traceId"])
    # zero-duration events never emit duration=0 (Zipkin drops them)
    sp0 = _zipkin_span("a" * 32, {"span": "s", "dur_s": 0.0})
    assert sp0["duration"] == 1


# ----------------------------------------------------------- drain on stop

def test_stop_drains_remaining_queue():
    """stop() must ship everything still queued (the final flush) —
    spans recorded just before shutdown are not silently dropped."""
    shipped = []
    # a long flush interval so the background thread never gets there
    # first: the drain must come from stop() itself
    exp = TraceExporter("http://unused.invalid/api/v2/spans",
                        flush_interval_s=60.0, batch=8)
    exp._ship = lambda spans: shipped.append(list(spans))
    exp.start()
    try:
        for i in range(20):
            exp.sink("b" * 32, _event(i))
    finally:
        exp.stop()
    assert sum(len(b) for b in shipped) == 20


# ------------------------------------------------------------ sink failure

def test_sink_failure_never_blocks_recording_path():
    """A dead collector must cost the query path NOTHING: sink() stays
    non-blocking (overflow drops are counted, never waited on), the
    export thread keeps running, and recovery resumes shipping."""
    calls = {"n": 0}
    broken = {"yes": True}

    def flaky_ship(spans):
        calls["n"] += 1
        if broken["yes"]:
            raise ConnectionError("collector down")

    exp = TraceExporter("http://unused.invalid/api/v2/spans",
                        flush_interval_s=0.02, max_queue=32, batch=8)
    exp._ship = flaky_ship
    err0 = registry.counter("trace_export_errors").value
    drop0 = registry.counter("trace_export_dropped").value
    exp.start()
    try:
        # flood well past the queue bound while the sink is failing:
        # every sink() call must return immediately
        t0 = time.perf_counter()
        for i in range(500):
            exp.sink("c" * 32, _event(i))
        assert time.perf_counter() - t0 < 1.0, "sink() blocked"
        deadline = time.time() + 5
        while time.time() < deadline and \
                registry.counter("trace_export_errors").value == err0:
            time.sleep(0.01)
        assert registry.counter("trace_export_errors").value > err0
        assert registry.counter("trace_export_dropped").value > drop0
        # the exporter job surfaced the streak (alertable via selfmon)
        from filodb_tpu.utils.jobs import jobs
        h = jobs.get("trace_export")
        assert h is not None and h.consecutive_errors >= 1
        # recovery: the sink heals, new spans ship again
        broken["yes"] = False
        exp.sink("d" * 32, _event(0))
        pre = calls["n"]
        deadline = time.time() + 5
        while time.time() < deadline and calls["n"] == pre:
            time.sleep(0.01)
        assert calls["n"] > pre
        assert h.consecutive_errors == 0     # note_ok reset the streak
    finally:
        exp.stop()


def test_sink_failure_does_not_wedge_query_serving():
    """End to end through the span pipeline: with the export sink
    attached to the collector and permanently failing, traced spans
    still record and complete at full speed — export is fire-and-forget
    off the serving path."""

    def dead_ship(spans):
        raise ConnectionError("collector down")

    exp = TraceExporter("http://unused.invalid/api/v2/spans",
                        flush_interval_s=0.02, max_queue=8)
    exp._ship = dead_ship
    exp.start()
    try:
        t0 = time.perf_counter()
        for i in range(200):
            with trace_context(f"{i:032x}"):
                with span("serving_probe"):
                    pass
        elapsed = time.perf_counter() - t0
        # 200 traced no-op spans must complete in well under a second
        # even with the exporter's queue full and its sink down
        assert elapsed < 1.0, f"span recording wedged: {elapsed:.3f}s"
        # and the collector still holds the traces (the in-memory store
        # is independent of export health)
        assert collector.trace(f"{199:032x}")
    finally:
        exp.stop()
