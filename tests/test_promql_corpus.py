"""PromQL conformance corpus — the shadow-parser replacement.

The reference runs two parsers in shadow mode in production and compares
results (ref: prometheus/.../parse/Parser.scala:13-70).  With one Pratt
parser, the substitute assurance is this corpus: test files transcribed
from the Prometheus-upstream promql testdata DSL (`load` blocks +
`eval instant at` cases), executed through the FULL engine stack
(parse -> plan -> exec -> kernels) and checked against hand-verified
expected values.

DSL subset supported:
    load <step>
      metric{l1="v1",...} v1 v2 _ 3+4x5 ...
    eval instant at <time> <expr>
      {labels} value            # one line per expected series
      metric{labels} value
(`a+bxN` / `a-bxN` expand to N+1 samples; `_` is a missing sample;
values may be NaN/Inf/-Inf.)

Documented divergence from upstream: FiloDB treats NaN samples as
ABSENT (the staleness marker), not as propagating float values — the
staleness.test cases encode the FiloDB semantics (see tests/oracle.py
and ref: AggrOverTimeFunctions NaN-skipping accumulators).
"""
import math
import os
import re

import numpy as np
import pytest

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "promql_corpus")

_DUR = {"s": 1, "m": 60, "h": 3600, "d": 86400}


def _dur_s(text):
    m = re.fullmatch(r"(\d+(?:\.\d+)?)([smhd])", text.strip())
    assert m, f"bad duration {text!r}"
    return float(m.group(1)) * _DUR[m.group(2)]


def _num(tok):
    t = tok.strip()
    if t in ("NaN", "nan"):
        return math.nan
    if t in ("Inf", "+Inf", "inf"):
        return math.inf
    if t == "-Inf":
        return -math.inf
    return float(t)


def _expand_values(tokens):
    """upstream series notation: literals, `_`, and a+bxN expansions."""
    out = []
    for tok in tokens:
        m = re.fullmatch(r"(-?\d+(?:\.\d+)?)([+-]\d+(?:\.\d+)?)x(\d+)", tok)
        if m:
            start, step, n = (float(m.group(1)), float(m.group(2)),
                              int(m.group(3)))
            out.extend(start + step * i for i in range(n + 1))
        elif tok == "_":
            out.append(None)
        else:
            out.append(_num(tok))
    return out


_SERIES_RE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)?(\{[^}]*\})?\s*(.*)$")


def _parse_labels(text):
    labels = {}
    body = text.strip()[1:-1].strip()
    if body:
        for part in re.findall(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"', body):
            labels[part[0]] = part[1]
    return labels


class Case:
    def __init__(self, at_s, expr, expected, line_no):
        self.at_s = at_s
        self.expr = expr
        self.expected = expected        # list of (metric, labels, value)
        self.line_no = line_no


def parse_corpus(path):
    """-> (load_step_s, series list [(metric, labels, values)], cases)."""
    step_s = None
    series = []
    cases = []
    cur = None
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            line = raw.rstrip("\n")
            stripped = line.strip()
            if not stripped or stripped.startswith("#"):
                continue
            if stripped.startswith("load "):
                step_s = _dur_s(stripped.split(None, 1)[1])
                cur = None
                continue
            if stripped.startswith("eval instant at "):
                rest = stripped[len("eval instant at "):]
                at, expr = rest.split(None, 1)
                cur = Case(_dur_s(at), expr, [], ln)
                cases.append(cur)
                continue
            if line[:1] in (" ", "\t"):
                m = _SERIES_RE.match(stripped)
                metric = m.group(1) or ""
                labels = _parse_labels(m.group(2)) if m.group(2) else {}
                rest = m.group(3).split()
                if cur is None:         # a load series
                    series.append((metric, labels, _expand_values(rest)))
                else:                   # an expected result line
                    assert len(rest) == 1, (path, ln, rest)
                    cur.expected.append((metric, labels, _num(rest[0])))
                continue
            raise AssertionError(f"{path}:{ln}: unparsable line {line!r}")
    return step_s, series, cases


def build_engine(step_s, series):
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatchBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine

    b = RecordBatchBuilder(DEFAULT_SCHEMAS["gauge"])
    for metric, labels, values in series:
        pk = PartKey.make(metric, labels)
        for i, v in enumerate(values):
            if v is None:
                continue
            b.add(pk, int(i * step_s * 1000), value=float(v))
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(b.build())
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    return QueryEngine("prometheus", ms, mapper)


# labels injected by the shard-key schema, not part of upstream semantics
_IMPL_LABELS = ("_ws_", "_ns_")


def _norm(metric, labels, strict_name):
    lab = {k: v for k, v in labels.items() if k not in _IMPL_LABELS}
    name = lab.pop("_metric_", lab.pop("__name__", metric or ""))
    return (name if strict_name else "",
            tuple(sorted(lab.items())))


def run_case(engine, case):
    res = engine.query_range(case.expr, case.at_s, 60, case.at_s)
    assert res.error is None, f"line {case.line_no}: {res.error}"
    got = {}
    # strict metric-name matching only when some expected line names one
    # (our engine keeps _metric_ through function application; upstream
    # drops it — value conformance is what this corpus pins down)
    strict = any(m for m, _, _ in case.expected)
    for k, _, v in res.series():
        vals = np.asarray(v, np.float64).reshape(-1)
        assert vals.size == 1, (case.expr, vals)
        got[_norm("", k.labels_dict, strict)] = float(vals[0])
    want = {_norm(m, dict(labels), strict): val
            for m, labels, val in case.expected}
    assert set(got) == set(want), (
        f"line {case.line_no}: {case.expr}\n  got keys  {sorted(got)}\n"
        f"  want keys {sorted(want)}")
    for key, val in want.items():
        g = got[key]
        if math.isnan(val):
            assert math.isnan(g), (case.line_no, case.expr, key, g)
        elif math.isinf(val):
            assert g == val, (case.line_no, case.expr, key, g)
        else:
            assert g == pytest.approx(val, rel=2e-5, abs=1e-4), (
                f"line {case.line_no}: {case.expr} {key}: "
                f"got {g}, want {val}")


def _corpus_files():
    return sorted(f for f in os.listdir(CORPUS_DIR)
                  if f.endswith(".test"))


@pytest.mark.parametrize("fname", _corpus_files())
def test_corpus_file(fname):
    path = os.path.join(CORPUS_DIR, fname)
    step_s, series, cases = parse_corpus(path)
    assert step_s and series and cases, path
    engine = build_engine(step_s, series)
    for case in cases:
        run_case(engine, case)


def test_classic_buckets_match_native_histogram_schema():
    """The histograms.test fixture replayed through the NATIVE histogram
    schema (bucket matrix column + bucket_les) must answer
    histogram_quantile identically to the classic `le`-labeled `_bucket`
    form — the two representations of the same histogram cannot diverge
    (ref: prometheus/.../PrometheusModel.scala bucket conversion)."""
    import numpy as np

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine

    # classic engine from the corpus fixture
    step_s, series, _ = parse_corpus(
        os.path.join(CORPUS_DIR, "histograms.test"))
    classic = build_engine(step_s, series)

    # native engine: the same job="a" ladder as one histogram column
    les = np.array([0.1, 0.5, 1.0, np.inf])
    slopes = np.array([1.0, 3.0, 5.0, 6.0])
    T = 21
    ts = np.arange(T, dtype=np.int64) * int(step_s * 1000)
    hist = slopes[None, :] * np.arange(T, dtype=np.float64)[:, None]
    schema = DEFAULT_SCHEMAS["prom-histogram"]
    pk = PartKey.make("req", {"job": "a"})
    batch = RecordBatch(
        schema, [pk], np.zeros(T, np.int32), ts,
        {"sum": hist[:, -1] * 2.0, "count": hist[:, -1].copy(),
         "h": hist}, bucket_les=les)
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(batch)
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    native = QueryEngine("prometheus", ms, mapper)

    at = 600
    for q in (0.25, 0.5, 0.75, 0.9, 1.0):
        rc = classic.query_range(
            f'histogram_quantile({q}, req_bucket{{job="a"}})', at, 60, at)
        rn = native.query_range(
            f'histogram_quantile({q}, req{{job="a"}})', at, 60, at)
        assert rc.error is None and rn.error is None, (rc.error, rn.error)
        vc = [float(np.asarray(v)[0]) for _, _, v in rc.series()]
        vn = [float(np.asarray(v)[0]) for _, _, v in rn.series()]
        assert len(vc) == len(vn) == 1, (q, vc, vn)
        np.testing.assert_allclose(vn, vc, rtol=1e-6, err_msg=f"q={q}")
    # the rate-then-quantile dashboard shape agrees too
    rc = classic.query_range(
        'histogram_quantile(0.5, rate(req_bucket{job="a"}[5m]))', at, 60, at)
    rn = native.query_range(
        'histogram_quantile(0.5, rate(req{job="a"}[5m]))', at, 60, at)
    vc = [float(np.asarray(v)[0]) for _, _, v in rc.series()]
    vn = [float(np.asarray(v)[0]) for _, _, v in rn.series()]
    np.testing.assert_allclose(vn, vc, rtol=1e-6)


def test_classic_bucket_quantile_survives_absent_bucket_samples():
    """A scrape gap in ONE `_bucket` series must not poison the group's
    quantile to NaN: the absent bucket fills down (no extra observations)
    and the remaining ladder still answers (review r4)."""
    import numpy as np

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatchBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine

    b = RecordBatchBuilder(DEFAULT_SCHEMAS["gauge"])
    for le, slope in (("0.1", 1), ("0.5", 3), ("1", 5), ("+Inf", 6)):
        pk = PartKey.make("req_bucket", {"job": "a", "le": le})
        for i in range(21):
            if le == "0.5" and i >= 15:
                continue                  # le=0.5 goes stale at minute 15
            b.add(pk, i * 60_000, value=float(slope * i))
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(b.build())
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    eng = QueryEngine("prometheus", ms, mapper)
    # at 20m the le=0.5 sample is past the 5m lookback -> absent slot
    res = eng.query_range(
        'histogram_quantile(0.9, req_bucket{job="a"})', 1200, 60, 1200)
    assert res.error is None, res.error
    out = [float(np.asarray(v)[0]) for _, _, v in res.series()]
    assert len(out) == 1 and np.isfinite(out[0]), out
    # ladder degrades to [10/le0.1, (fill)10, 100/le1, 120/Inf]:
    # rank 108 -> +Inf bucket -> highest finite le
    assert out[0] == pytest.approx(1.0), out
