"""Cross-node trace propagation + per-series debug follow (ref:
query/.../exec/ExecPlan.scala:102-131 Kamon spans through distributed
exec; KamonLogger.scala:16-40; README.md:871-875 tracedPartFilters)."""
import json
import logging
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.ingest.generator import counter_batch, gauge_batch
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
from filodb_tpu.parallel.testcluster import make_two_node_cluster
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.utils.metrics import collector, registry

START = 1_600_000_000_000
START_S = START // 1000


def test_cross_node_query_stitches_one_trace():
    """A scatter-gather query across two node servers produces ONE trace:
    the coordinator's spans plus each remote node's spans (shipped back in
    the dispatch reply), under the query's trace id."""
    cluster = make_two_node_cluster(
        [counter_batch(24, 120, start_ms=START)])
    try:
        res = cluster.engine.query_range(
            'sum by (_ns_)(rate(request_total[5m]))',
            START_S + 600, 60, START_S + 1200)
        assert res.error is None, res.error
        assert res.trace_id, "query result must carry its trace id"
        evs = collector.trace(res.trace_id)
        names = [e["span"] for e in evs]
        # remote subtree spans crossed the wire, tagged with their plan:
        # with aggregation pushdown the dispatched subtree is the node's
        # RemoteAggregateExec group (one per NODE, not per shard)
        remotes = [e for e in evs if e["span"].startswith("remote_exec")]
        assert remotes and all(
            r.get("plan") == "RemoteAggregateExec" for r in remotes)
        # one per dispatched node group (2 nodes x 2 shards), no
        # duplication from the drain-per-reply protocol
        assert len(remotes) == 2, names
        # and the coordinator's root plan span is present
        assert any(n == "execplan" or n.startswith("execplan")
                   for n in names), names
    finally:
        cluster.stop()


def test_trace_ids_isolate_queries():
    cluster = make_two_node_cluster(
        [gauge_batch(8, 60, start_ms=START)])
    try:
        r1 = cluster.engine.query_range('sum(heap_usage)', START_S + 120,
                                        60, START_S + 500)
        r2 = cluster.engine.query_range('sum(heap_usage)', START_S + 120,
                                        60, START_S + 500)
        assert r1.trace_id and r2.trace_id and r1.trace_id != r2.trace_id
        assert collector.trace(r1.trace_id)
        assert collector.trace(r2.trace_id)
    finally:
        cluster.stop()


def test_traces_and_traceid_over_http():
    """traceID rides the Prometheus JSON response; /admin/traces/<id>
    returns the stitched span tree."""
    from filodb_tpu.http.routes import PromHttpApi
    from filodb_tpu.http.server import FiloHttpServer
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(8, 60, start_ms=START))
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    eng = QueryEngine("prometheus", ms, mapper)
    srv = FiloHttpServer(PromHttpApi({"prometheus": eng}), port=0)
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.port}/promql/prometheus/api/v1/"
               f"query_range?query=sum(heap_usage)&start={START_S + 120}"
               f"&end={START_S + 500}&step=60")
        with urllib.request.urlopen(url, timeout=60) as r:
            d = json.load(r)
        assert d["status"] == "success" and d.get("traceID")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/admin/traces/{d['traceID']}",
                timeout=60) as r:
            tr = json.load(r)
        spans = tr["data"]["spans"]
        assert spans and all("span" in e and "dur_s" in e for e in spans)
        assert any(e["span"].startswith("execplan") for e in spans)
        # trace listing contains the id
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/admin/traces",
                timeout=60) as r:
            ids = json.load(r)["data"]
        assert d["traceID"] in ids
    finally:
        srv.stop()


# --------------------------------------------- per-series debug follow

def test_traced_filters_follow_ingest_and_query(caplog):
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(10, 5, start_ms=START))
    n = sh.set_traced_filters([{"_ns_": "App-1"}])
    assert n >= 1, "existing matching series should be found"
    before = registry.counter("traced_series_events", dataset="prometheus",
                              event="ingest").value
    with caplog.at_level(logging.INFO, logger="filodb.shard"):
        sh.ingest(gauge_batch(10, 3, start_ms=START + 60_000))
        from filodb_tpu.core.index import Equals
        sh.lookup_partitions([Equals("_ns_", "App-1")], START,
                             START + 600_000)
    msgs = [r.getMessage() for r in caplog.records if "TRACED" in r.message]
    assert any("ingest" in m and "App-1" in m for m in msgs), msgs
    assert any("query_lookup" in m for m in msgs), msgs
    after = registry.counter("traced_series_events", dataset="prometheus",
                             event="ingest").value
    assert after > before
    # clearing stops the follow
    assert sh.set_traced_filters([]) == 0
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="filodb.shard"):
        sh.ingest(gauge_batch(10, 2, start_ms=START + 120_000))
    assert not [r for r in caplog.records if "TRACED" in r.message]


def test_traced_filters_via_http_admin():
    from filodb_tpu.http.routes import PromHttpApi
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(gauge_batch(6, 5, start_ms=START))
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    eng = QueryEngine("prometheus", ms, mapper)
    api = PromHttpApi({"prometheus": eng})
    status, payload = api.handle(
        "POST", "/admin/tracedfilters", {},
        json.dumps([{"_ns_": "App-0"}]).encode())
    assert status == 200 and payload["data"]["shards"] == 1
    assert sh._traced_pids, "filter should mark matching partitions"
    status, payload = api.handle("POST", "/admin/tracedfilters", {}, b"[]")
    assert status == 200 and not sh._traced_pids


def test_trace_export_file_and_http(tmp_path):
    """Round-5 missing #3 (ref: KamonLogger.scala:16-40 span reporters):
    spans PUSH out of the process — Zipkin v2 JSON to a file sink and to
    an HTTP collector — while the in-memory store stays bounded."""
    import http.server
    import json as _json
    import threading
    import time as _time

    from filodb_tpu.utils.metrics import collector, span, trace_context
    from filodb_tpu.utils.traceexport import TraceExporter

    # file sink
    path = tmp_path / "spans.jsonl"
    exp = TraceExporter(f"file://{path}", flush_interval_s=0.05).start()
    try:
        with trace_context("11111111-2222-3333-4444-555555555555"):
            with span("execplan", plan="TestExec"):
                _time.sleep(0.01)
        deadline = _time.time() + 5
        while _time.time() < deadline and not path.exists():
            _time.sleep(0.05)
        assert path.exists()
        lines = [_json.loads(ln) for ln in path.read_text().splitlines()]
        sp = next(s for s in lines if s["name"].endswith("execplan"))
        assert sp["traceId"] == "11111111222233334444555555555555"
        assert sp["duration"] >= 10_000          # >= 10ms in microseconds
        assert sp["tags"]["plan"] == "TestExec"
        assert sp["localEndpoint"]["serviceName"]
    finally:
        exp.stop()

    # HTTP sink: a fake Zipkin collector records POSTed batches
    got = []

    class _Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            got.extend(_json.loads(self.rfile.read(n)))
            self.send_response(202)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), _Collector)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    exp2 = TraceExporter(
        f"http://127.0.0.1:{srv.server_port}/api/v2/spans",
        flush_interval_s=0.05).start()
    try:
        with trace_context("aaaaaaaa-bbbb-cccc-dddd-eeeeeeeeeeee"):
            with span("leafexec"):
                pass
        deadline = _time.time() + 5
        while _time.time() < deadline and not got:
            _time.sleep(0.05)
        assert any(s["traceId"] == "aaaaaaaabbbbccccddddeeeeeeeeeeee"
                   for s in got)
    finally:
        exp2.stop()
        srv.shutdown()

    # detached sinks stop receiving; store retention stays bounded
    before = len(got)
    with trace_context("aaaaaaaa-bbbb-cccc-dddd-eeeeeeeeeeee"):
        with span("after_stop"):
            pass
    assert len(got) == before
    assert len(collector.trace_ids()) <= collector.max_traces
