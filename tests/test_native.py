"""Native C++ acceleration parity tests (models ref: the bit-compat contract
between lz4-java native XXHash and its JVM fallback, and NibblePackTest).

Skipped when the shared library could not be built; the Python fallbacks are
covered by test_hashing.py / test_nibblepack.py either way.
"""
import numpy as np
import pytest

from filodb_tpu.native import lib as native

pytestmark = pytest.mark.skipif(native is None,
                                reason="native library not built")

from filodb_tpu.utils import hashing as H               # noqa: E402
from filodb_tpu.memory import nibblepack as NP          # noqa: E402


def _py_xxhash32(data, seed=0):
    return getattr(H, "_py_xxhash32", H.xxhash32)(data, seed)


def _py_xxhash64(data, seed=0):
    return getattr(H, "_py_xxhash64", H.xxhash64)(data, seed)


@pytest.mark.parametrize("n", [0, 1, 3, 4, 5, 15, 16, 17, 31, 32, 33, 255,
                               1024])
def test_xxhash32_parity(n, rng):
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    for seed in (0, 1, 0xDEADBEEF):
        assert native.xxhash32(data, seed) == _py_xxhash32(data, seed)


@pytest.mark.parametrize("n", [0, 1, 7, 8, 9, 31, 32, 33, 255, 1024])
def test_xxhash64_parity(n, rng):
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    for seed in (0, 7, 2**63):
        assert native.xxhash64(data, seed) == _py_xxhash64(data, seed)


def test_hashing_module_uses_native():
    # utils.hashing must have swapped in the native implementation
    assert getattr(H, "_py_xxhash32", None) is not None


@pytest.mark.parametrize("case", ["zeros", "small", "large", "mixed",
                                  "full64", "ragged"])
def test_nibblepack_parity(case, rng):
    if case == "zeros":
        vals = np.zeros(64, dtype=np.uint64)
    elif case == "small":
        vals = rng.integers(0, 16, 64).astype(np.uint64)
    elif case == "large":
        vals = rng.integers(0, 2**62, 64).astype(np.uint64)
    elif case == "mixed":
        vals = rng.integers(0, 2**30, 64).astype(np.uint64)
        vals[::3] = 0
    elif case == "full64":
        vals = np.full(16, 0xFFFFFFFFFFFFFFFF, dtype=np.uint64)
    else:
        vals = rng.integers(0, 1000, 13).astype(np.uint64)   # non-multiple of 8
    c_packed = native.nibble_pack(vals)
    py_packed = NP._pack_py(vals)
    assert c_packed == py_packed, "wire bytes must be identical"
    # cross-decode both ways
    np.testing.assert_array_equal(native.nibble_unpack(py_packed, len(vals)),
                                  vals)
    np.testing.assert_array_equal(NP._unpack_py(c_packed, len(vals)), vals)


def test_nibblepack_roundtrip_fuzz(rng):
    for _ in range(50):
        n = int(rng.integers(1, 200))
        shift = int(rng.integers(0, 12)) * 4
        vals = (rng.integers(0, 2**52, n).astype(np.uint64)
                << np.uint64(shift))
        packed = native.nibble_pack(vals)
        assert packed == NP._pack_py(vals)
        np.testing.assert_array_equal(native.nibble_unpack(packed, n), vals)


def test_unpack_truncated_raises():
    vals = np.arange(1, 17, dtype=np.uint64)
    packed = native.nibble_pack(vals)
    with pytest.raises(ValueError):
        native.nibble_unpack(packed[:3], 16)


def test_timestamp_codec_through_native():
    ts = 1_600_000_000_000 + np.arange(720, dtype=np.int64) * 10_000
    ts[37] += 3
    base, slope, payload = NP.pack_timestamps(ts)
    out = NP.unpack_timestamps(base, slope, payload, len(ts))
    np.testing.assert_array_equal(out, ts)
