"""BinaryHistogram wire blobs + section-based appendable storage
(ref: memory/.../vectors/HistogramVector.scala:17-34 BinaryHistogram,
:427 AppendableSectDeltaHistVector; doc/compression.md:33-97)."""
import numpy as np
import pytest

from filodb_tpu.memory.binhist import (AppendableSectHistVector,
                                       CustomScheme, GeometricScheme,
                                       decode_blob, decode_blob_column,
                                       detect_scheme, encode_blob,
                                       encode_blob_column)


def _hist_series(T=100, B=8, seed=0):
    rng = np.random.default_rng(seed)
    inc = rng.poisson(3.0, size=(T, B))
    per_bucket = np.cumsum(inc, axis=0)        # cumulative over time
    return np.cumsum(per_bucket, axis=1).astype(np.float64)  # over buckets


def test_scheme_detection_and_roundtrip():
    geo = detect_scheme(np.array([2.0, 4.0, 8.0, 16.0]))
    assert isinstance(geo, GeometricScheme) and geo.multiplier == 2.0
    np.testing.assert_allclose(geo.les(), [2, 4, 8, 16])
    cus = detect_scheme(np.array([0.5, 2.0, 8.0, np.inf]))
    assert isinstance(cus, CustomScheme)
    np.testing.assert_array_equal(cus.les(), [0.5, 2.0, 8.0, np.inf])


@pytest.mark.parametrize("les", [
    np.array([2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0]),
    np.array([0.25, 1.0, 2.5, 10.0, np.inf])])
def test_blob_roundtrip_integral(les):
    mat = _hist_series(B=len(les))
    for row in mat:
        blob = encode_blob(row, les=les)
        values, scheme, used = decode_blob(blob)
        assert used == len(blob)
        np.testing.assert_array_equal(values, row)
        np.testing.assert_allclose(scheme.les(), les)


def test_blob_roundtrip_double_values():
    les = np.array([1.0, 2.0, 4.0, np.inf])
    row = np.array([0.25, 1.5, 2.75, 3.125])
    blob = encode_blob(row, les=les)
    values, _, _ = decode_blob(blob)
    np.testing.assert_allclose(values, row)


def test_blob_column_roundtrip():
    les = np.array([2.0, 4.0, 8.0, 16.0])
    mat = _hist_series(T=50, B=4)
    data = encode_blob_column(mat, les)
    got, got_les = decode_blob_column(data, 50)
    np.testing.assert_array_equal(got, mat)
    np.testing.assert_allclose(got_les, les)


def test_blob_much_smaller_than_raw():
    """The point of the format: ingest blobs are a fraction of raw f64
    bucket rows (ref doc/compression.md:97 measures ~1/5 at B=64)."""
    les = 2.0 * 2.0 ** np.arange(64)
    mat = _hist_series(T=200, B=64, seed=3)
    data = encode_blob_column(mat, les)
    raw = mat.size * 8
    assert len(data) < raw / 3, (len(data), raw)


def test_section_vector_roundtrip_and_sections():
    les = np.array([2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0])
    mat = _hist_series(T=100, B=8, seed=1)
    vec = AppendableSectHistVector(les, section_limit=16)
    for row in mat:
        vec.append(row)
    assert vec.num_histograms == 100
    got = AppendableSectHistVector.decode(vec.to_bytes())
    np.testing.assert_array_equal(got, mat)
    # delta-against-section-start keeps it smaller than independent blobs
    blobs = encode_blob_column(mat, les)
    assert vec.num_bytes < len(blobs), (vec.num_bytes, len(blobs))


def test_section_vector_counter_drop_starts_new_section():
    """A bucket dropping below the section start (counter reset) must roll
    the section, and decode must still reproduce the data exactly."""
    les = np.array([2.0, 4.0, 8.0])
    rows = [np.array([5.0, 10.0, 20.0]),
            np.array([7.0, 12.0, 25.0]),
            np.array([1.0, 2.0, 3.0]),        # reset
            np.array([4.0, 6.0, 9.0])]
    vec = AppendableSectHistVector(les, section_limit=16)
    for r in rows:
        vec.append(r)
    assert len(vec._sections) == 2
    got = AppendableSectHistVector.decode(vec.to_bytes())
    np.testing.assert_array_equal(got, np.stack(rows))


def test_record_batch_wire_carries_blobs():
    """gateway->broker->node frames: the hist column of a v2 RecordBatch
    round-trips through BinaryHistogram blobs and shrinks on the wire."""
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.ingest.generator import histogram_batch
    batch = histogram_batch(12, 80)
    wire = batch.to_bytes()
    back = RecordBatch.from_bytes(wire)
    np.testing.assert_array_equal(back.columns["h"], batch.columns["h"])
    np.testing.assert_array_equal(back.timestamps, batch.timestamps)
    np.testing.assert_allclose(back.bucket_les, batch.bucket_les)
    raw_hist_bytes = batch.columns["h"].size * 8
    blob_bytes = len(encode_blob_column(batch.columns["h"],
                                        batch.bucket_les))
    assert blob_bytes < raw_hist_bytes * 0.7, (blob_bytes, raw_hist_bytes)
    # and the whole frame shrank vs the v1 raw-matrix encoding
    assert len(wire) < raw_hist_bytes + 30_000


def test_blob_minus_one_geometric_xor_preserves_les():
    """Non-integral values on a minus_one geometric scheme must not lose
    the -1 adjustment (no geometric_1 XOR format exists; the encoder
    widens to a custom scheme)."""
    scheme = GeometricScheme(2.0, 2.0, 4, minus_one=True)
    row = np.array([0.5, 1.25, 2.75, 3.0625])
    blob = encode_blob(row, scheme=scheme)
    values, back, _ = decode_blob(blob)
    np.testing.assert_allclose(values, row)
    np.testing.assert_allclose(back.les(), scheme.les())
