"""Layered config system (ref: FilodbSettings.scala:127 — defaults <- file
<- overrides, validated; filodb-defaults.conf `filodb.schemas` declarations)."""
import pytest

from filodb_tpu.config import ConfigError, FilodbSettings
from filodb_tpu.utils import hoconlite
from filodb_tpu.utils.hoconlite import Duration


# ------------------------------------------------------------- hocon-lite

def test_hocon_basic_types_and_nesting():
    cfg = hoconlite.loads("""
    // top comment
    filodb {
      spread_default = 2          # inline comment
      query {
        sample_limit = 500000
        faster_rate = off
      }
      store.flush_interval_ms = 1h
      tags = [a, "b c", 3]
    }
    """)
    f = cfg["filodb"]
    assert f["spread_default"] == 2
    assert f["query"]["sample_limit"] == 500_000
    assert f["query"]["faster_rate"] is False
    assert f["store"]["flush_interval_ms"] == Duration(3_600_000.0)
    assert f["tags"] == ["a", "b c", 3]


def test_hocon_duplicate_blocks_merge_later_wins():
    cfg = hoconlite.loads("""
    a {
      x = 1
      y = 2
    }
    a.x = 9
    """)
    assert cfg["a"] == {"x": 9, "y": 2}


def test_hocon_durations():
    cfg = hoconlite.loads("t1 = 500ms\nt2 = 5 seconds\nt3 = 2 hours")
    assert cfg["t1"].millis == 500
    assert cfg["t2"].seconds == 5
    assert cfg["t3"].millis == 2 * 3_600_000


def test_hocon_errors():
    with pytest.raises(hoconlite.HoconError):
        hoconlite.loads("a {\n b = 1")
    with pytest.raises(hoconlite.HoconError):
        hoconlite.loads("}")


# ---------------------------------------------------------------- layering

def test_file_layer_hocon(tmp_path):
    p = tmp_path / "filodb.conf"
    p.write_text("""
    filodb {
      spread_default = 3
      query.sample_limit = 42
      store.flush_interval_ms = 30 minutes
    }
    """)
    s = FilodbSettings.load(str(p), env={})
    assert s.spread_default == 3
    assert s.query.sample_limit == 42
    assert s.store.flush_interval_ms == 30 * 60 * 1000
    # untouched defaults remain
    assert s.store.groups_per_shard == 60


def test_env_layer_overrides_file(tmp_path):
    p = tmp_path / "filodb.conf"
    p.write_text("filodb.query.sample_limit = 42")
    s = FilodbSettings.load(str(p), env={
        "FILODB_QUERY_SAMPLE_LIMIT": "77",
        "FILODB_STORE_DEVICE_MIRROR_ENABLED": "false",
        "FILODB_SPREAD_DEFAULT": "4",
    })
    assert s.query.sample_limit == 77
    assert s.store.device_mirror_enabled is False
    assert s.spread_default == 4


def test_env_durations_and_foreign_vars():
    s = FilodbSettings.load(None, env={
        "FILODB_STORE_FLUSH_INTERVAL_MS": "30 minutes",
        "FILODB_BENCH_TPU_TIMEOUT": "600",    # sibling tool's var: ignored
        "FILODB_TPU_CONFIG": "/nonexistent",  # the pointer itself: ignored
    })
    assert s.store.flush_interval_ms == 30 * 60 * 1000
    # typos inside the query_/store_ namespaces still raise
    with pytest.raises(ConfigError):
        FilodbSettings.load(None, env={"FILODB_QUERY_SAMPLE_LIMITT": "5"})


def test_partition_schema_top_level_typo_raises():
    with pytest.raises(ConfigError, match="optionz"):
        FilodbSettings().overlay(
            {"partition_schema": {"optionz": {"metric_column": "m"}}})


def test_spread_assignment_hocon_gives_config_error():
    with pytest.raises(ConfigError, match="spread_assignment"):
        FilodbSettings().overlay({"spread_assignment": ["{ garbled }"]})


def test_config_schemas_flow_into_memstore():
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    s = FilodbSettings().overlay({"schemas": {
        "env-schema": {"columns": ["timestamp:ts", "v:double"],
                       "value_column": "v"}}})
    ms = TimeSeriesMemStore(config=s)
    assert "env-schema" in ms.schemas      # no per-call-site plumbing


def test_unknown_key_raises_with_path(tmp_path):
    p = tmp_path / "filodb.conf"
    p.write_text("filodb.query.sample_limitt = 42")
    with pytest.raises(ConfigError, match="sample_limitt"):
        FilodbSettings.load(str(p), env={})


def test_type_validation():
    with pytest.raises(ConfigError, match="boolean"):
        FilodbSettings().overlay({"query": {"faster_rate": "maybe"}})
    with pytest.raises(ConfigError, match="integer"):
        FilodbSettings().overlay({"query": {"sample_limit": 1.5}})
    with pytest.raises(ConfigError, match="non-duration"):
        FilodbSettings().overlay({"query": {"sample_limit": Duration(5.0)}})


# ------------------------------------------------------- declared schemas

SCHEMA_CONF = """
filodb {
  schemas {
    temp-sensor {
      columns = ["timestamp:ts", "reading:double", "errors:double:detect_drops"]
      value_column = reading
    }
  }
  partition_schema.options.shard_key_columns = [_ws_, _ns_, _metric_]
}
"""


def test_config_declared_schema(tmp_path):
    p = tmp_path / "filodb.conf"
    p.write_text(SCHEMA_CONF)
    s = FilodbSettings.load(str(p), env={})
    assert s.schemas is not None
    sch = s.schemas["temp-sensor"]
    assert sch.value_column == "reading"
    assert sch.column("errors").detect_drops
    # built-ins still present
    assert "prom-counter" in s.schemas


def test_config_declared_schema_is_usable(tmp_path):
    """A config-declared schema must flow into a working server."""
    import numpy as np
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.query.engine import QueryEngine
    p = tmp_path / "filodb.conf"
    p.write_text(SCHEMA_CONF)
    s = FilodbSettings.load(str(p), env={})
    ms = TimeSeriesMemStore(schemas=s.schemas)
    ms.setup("prometheus", 0)
    START = 1_600_000_000_000
    keys = [PartKey.make("room_temp", {"_ws_": "w", "_ns_": "n",
                                       "instance": f"i{i}"}) for i in range(3)]
    n = 60
    batch = RecordBatch(
        s.schemas["temp-sensor"], keys,
        np.repeat(np.arange(3, dtype=np.int32), n),
        np.tile(START + np.arange(n, dtype=np.int64) * 10_000, 3),
        {"reading": np.arange(3 * n, dtype=np.float64),
         "errors": np.zeros(3 * n)})
    ms.ingest("prometheus", 0, batch, offset=1)
    eng = QueryEngine("prometheus", ms)
    res = eng.query_range('sum(room_temp)', START // 1000 + 60, 60,
                          START // 1000 + 500)
    assert res.error is None, res.error
    assert len(list(res.series())) == 1


@pytest.mark.parametrize("bad,msg", [
    ({"schemas": {"x": {"columns": ["t:ts"], "value_column": "nope"}}},
     "value_column"),
    ({"schemas": {"x": {"columns": ["v:double"], "value_column": "v"}}},
     "first column"),
    ({"schemas": {"x": {"columns": ["t:ts", "v:blob"],
                        "value_column": "v"}}}, "name:type"),
    ({"schemas": {"x": {"columns": ["t:ts", "v:double:bogus"],
                        "value_column": "v"}}}, "unknown flags"),
    ({"schemas": {"x": {"columns": ["t:ts", "v:double"], "value_column": "v",
                        "downsample_schema": "ghost"}}}, "not defined"),
])
def test_schema_validation_errors(bad, msg):
    with pytest.raises(ConfigError, match=msg):
        FilodbSettings().overlay(bad)
