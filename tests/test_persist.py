"""Local-disk persistence tests — the Cassandra-analogue backend
(models ref: cassandra/src/test + crash-consistency of the checkpoint
protocol, doc/ingestion.md:114-133)."""
import os
import struct

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.store import PartKeyRecord
from filodb_tpu.ingest.generator import (gauge_batch, histogram_batch,
                                         batch_stream)
from filodb_tpu.memory.chunks import encode_chunkset, decode_chunkset
from filodb_tpu.persist import LocalDiskColumnStore, LocalDiskMetaStore


def _sample_chunkset(n=20, start_ms=0, ing_ms=123_000):
    ts = np.arange(n, dtype=np.int64) * 10_000 + start_ms
    vals = np.sin(np.arange(n) / 3.0) * 50 + 100
    return ts, vals, encode_chunkset(ts, {"value": vals}, {"value": "double"},
                                     ing_ms)


def test_chunk_roundtrip_disk(tmp_path):
    store = LocalDiskColumnStore(str(tmp_path))
    store.initialize("prometheus", 2)
    pk = PartKey.make("m", {"_ws_": "w", "_ns_": "n", "instance": "i0"})
    ts, vals, cs = _sample_chunkset()
    store.write_chunks("prometheus", 0, pk, [cs], "gauge")
    store.close()

    # fresh open: index rebuilt by scanning the log
    store2 = LocalDiskColumnStore(str(tmp_path))
    out = store2.read_chunks("prometheus", 0, pk, 0, 10**15)
    assert len(out) == 1
    decoded = decode_chunkset(out[0])
    np.testing.assert_array_equal(decoded["timestamp"], ts)
    np.testing.assert_allclose(decoded["value"], vals)
    assert out[0].info.ingestion_time_ms == 123_000
    # time-range filter excludes
    assert store2.read_chunks("prometheus", 0, pk, 10**12, 10**15) == []


def test_partkey_upsert_last_wins(tmp_path):
    store = LocalDiskColumnStore(str(tmp_path))
    pk = PartKey.make("m", {"_ws_": "w", "_ns_": "n"})
    store.write_part_keys("p", 0, [PartKeyRecord(pk, "gauge", 100, 200)])
    store.write_part_keys("p", 0, [PartKeyRecord(pk, "gauge", 100, 900)])
    store.close()
    store2 = LocalDiskColumnStore(str(tmp_path))
    recs = store2.read_part_keys("p", 0)
    assert len(recs) == 1
    assert recs[0].end_time_ms == 900


def test_torn_tail_tolerated(tmp_path):
    store = LocalDiskColumnStore(str(tmp_path))
    pk = PartKey.make("m", {"_ws_": "w", "_ns_": "n"})
    for i in range(3):
        _, _, cs = _sample_chunkset(start_ms=i * 1_000_000)
        store.write_chunks("p", 0, pk, [cs], "gauge")
    store.close()
    path = os.path.join(str(tmp_path), "p", "shard-0", "chunks.log")
    # simulate a crash mid-append: truncate the last frame
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 17)
    store2 = LocalDiskColumnStore(str(tmp_path))
    out = store2.read_chunks("p", 0, pk, 0, 10**15)
    assert len(out) == 2  # last good frames survive, torn tail dropped


def test_corrupt_frame_stops_scan(tmp_path):
    store = LocalDiskColumnStore(str(tmp_path))
    pk = PartKey.make("m", {"_ws_": "w", "_ns_": "n"})
    _, _, cs = _sample_chunkset()
    store.write_chunks("p", 0, pk, [cs], "gauge")
    store.close()
    path = os.path.join(str(tmp_path), "p", "shard-0", "chunks.log")
    with open(path, "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad")    # flip bytes inside the payload -> CRC mismatch
    store2 = LocalDiskColumnStore(str(tmp_path))
    assert store2.read_chunks("p", 0, pk, 0, 10**15) == []


def test_histogram_chunk_roundtrip_disk(tmp_path):
    from filodb_tpu.memory.histogram import default_buckets
    store = LocalDiskColumnStore(str(tmp_path))
    pk = PartKey.make("lat", {"_ws_": "w", "_ns_": "n"})
    n, scheme = 16, default_buckets()
    ts = np.arange(n, dtype=np.int64) * 10_000
    mat = np.cumsum(np.random.default_rng(0).integers(
        0, 5, size=(n, scheme.num_buckets)), axis=1).astype(np.int64)
    cs = encode_chunkset(ts, {"h": mat}, {"h": "hist"}, 1_000, scheme)
    store.write_chunks("p", 0, pk, [cs], "prom-histogram")
    store.close()
    out = LocalDiskColumnStore(str(tmp_path)).read_chunks("p", 0, pk, 0, 10**15)
    assert out[0].bucket_scheme == scheme
    np.testing.assert_array_equal(decode_chunkset(out[0])["h"], mat)


def test_ingestion_time_scan(tmp_path):
    store = LocalDiskColumnStore(str(tmp_path))
    pk = PartKey.make("m", {"_ws_": "w", "_ns_": "n"})
    for ing in (100_000, 200_000, 300_000):
        _, _, cs = _sample_chunkset(ing_ms=ing)
        store.write_chunks("p", 0, pk, [cs], "gauge")
    hits = list(store.scan_chunks_by_ingestion_time("p", 0, 150_000, 300_000))
    assert len(hits) == 1
    assert hits[0][2].info.ingestion_time_ms == 200_000
    assert hits[0][0] == pk
    assert hits[0][1] == "gauge"


def test_metastore_checkpoints_atomic(tmp_path):
    meta = LocalDiskMetaStore(str(tmp_path))
    meta.write_checkpoint("p", 0, 0, 10)
    meta.write_checkpoint("p", 0, 1, 20)
    meta.write_checkpoint("p", 0, 0, 30)
    meta2 = LocalDiskMetaStore(str(tmp_path))
    assert meta2.read_checkpoints("p", 0) == {0: 30, 1: 20}
    assert meta2.read_earliest_checkpoint("p", 0) == 20
    assert meta2.read_highest_checkpoint("p", 0) == 30
    assert meta2.read_checkpoints("p", 1) == {}


def test_full_crash_recovery_via_disk(tmp_path):
    """End-to-end: ingest -> flush to disk -> process 'dies' -> a fresh
    memstore recovers the index from disk and replays only unflushed offsets
    (mirrors ref: standalone/src/multi-jvm IngestionAndRecoverySpec)."""
    cs = LocalDiskColumnStore(str(tmp_path / "col"))
    meta = LocalDiskMetaStore(str(tmp_path / "meta"))
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard = ms.setup("prometheus", 0)
    batch = gauge_batch(6, 40)
    stream = list(batch_stream(batch, samples_per_chunk=10))
    for b, off in stream[:2]:
        shard.ingest(b, off)
    shard.flush_all_groups()
    for b, off in stream[2:]:      # ingested but never flushed
        shard.ingest(b, off)
    cs.close()

    cs2 = LocalDiskColumnStore(str(tmp_path / "col"))
    meta2 = LocalDiskMetaStore(str(tmp_path / "meta"))
    ms2 = TimeSeriesMemStore(column_store=cs2, meta_store=meta2)
    shard2 = ms2.setup("prometheus", 0)
    assert shard2.recover_index() == 6
    replayed = shard2.recover_stream(stream)
    assert replayed == 2 * 6 * 10   # only the unflushed offsets
    # queries over the recovered shard see full data
    parts = shard2.lookup_partitions([], 0, 10**15)
    assert len(parts.part_ids) == 6


def test_odp_pages_flushed_chunks_for_query(tmp_path):
    """After recovery, flushed history lives only on disk; the leaf exec must
    page it back in on demand (ref: OnDemandPagingShard.scala:27-39) so a
    PromQL query over the full range sees every sample."""
    from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider
    from filodb_tpu.query.engine import QueryEngine

    cs = LocalDiskColumnStore(str(tmp_path / "col"))
    meta = LocalDiskMetaStore(str(tmp_path / "meta"))
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    shard = ms.setup("prometheus", 0)
    start_ms = 1_000_000
    batch = gauge_batch(8, 120, start_ms=start_ms)
    stream = list(batch_stream(batch, samples_per_chunk=30))
    for b, off in stream[:2]:
        shard.ingest(b, off)
    shard.flush_all_groups()           # first 60 samples per series -> disk
    for b, off in stream[2:]:
        shard.ingest(b, off)
    cs.close()

    cs2 = LocalDiskColumnStore(str(tmp_path / "col"))
    ms2 = TimeSeriesMemStore(column_store=cs2,
                             meta_store=LocalDiskMetaStore(str(tmp_path / "meta")))
    shard2 = ms2.setup("prometheus", 0)
    shard2.recover_index()
    shard2.recover_stream(stream)      # replays only the unflushed 60

    mapper = ShardMapper(1)
    mapper.register_node([0], "local")
    engine = QueryEngine("prometheus", ms2, mapper, SpreadProvider(0))
    start_s = start_ms // 1000
    res = engine.query_range('sum_over_time(heap_usage{_ws_="demo"}[20m])',
                             start_s + 1200, 60, start_s + 1200)
    assert res.error is None
    assert res.num_series == 8
    # every series' full 120 samples contribute (the first 60 via ODP);
    # the window (start, start+20m] is left-open so sample 0 is excluded
    vals = batch.columns["value"].reshape(8, 120)
    total = sum(float(v[0]) for _, _, v in res.series())
    np.testing.assert_allclose(total, vals[:, 1:].sum(), rtol=1e-9)
    # histogram ODP: bucket matrices round-trip through prepend
    cs3 = LocalDiskColumnStore(str(tmp_path / "hist"))
    ms3 = TimeSeriesMemStore(column_store=cs3,
                             meta_store=LocalDiskMetaStore(str(tmp_path / "hmeta")))
    sh = ms3.setup("prometheus", 0)
    hb = histogram_batch(3, 40, start_ms=start_ms)
    hstream = list(batch_stream(hb, samples_per_chunk=20))
    sh.ingest(*hstream[0])
    sh.flush_all_groups()
    cs3.close()
    cs4 = LocalDiskColumnStore(str(tmp_path / "hist"))
    ms4 = TimeSeriesMemStore(column_store=cs4,
                             meta_store=LocalDiskMetaStore(str(tmp_path / "hmeta")))
    sh2 = ms4.setup("prometheus", 0)
    sh2.recover_index()
    look = sh2.lookup_partitions([], 0, 10**15)
    parts = look.parts_by_schema["prom-histogram"]
    assert sh2.ensure_paged(parts, 0, 10**15) == 3 * 20
    ts, cols, counts, store = sh2.gather_series(parts)
    assert cols["h"].shape[2] == store.num_buckets
    assert np.isfinite(cols["h"][:, :20, :]).all()


def test_odp_clamps_to_query_range(tmp_path):
    """A narrow query over a recovered (empty-row) partition must only page
    chunks overlapping the query window, not the entire persisted history."""
    cs = LocalDiskColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(column_store=cs,
                            meta_store=LocalDiskMetaStore(str(tmp_path)))
    shard = ms.setup("p", 0)
    start_ms = 1_000_000
    stream = list(batch_stream(gauge_batch(2, 120, start_ms=start_ms),
                               samples_per_chunk=30))
    for b, off in stream:
        shard.ingest(b, off)
    shard.flush_all_groups()
    cs.close()

    ms2 = TimeSeriesMemStore(column_store=LocalDiskColumnStore(str(tmp_path)),
                             meta_store=LocalDiskMetaStore(str(tmp_path)))
    sh2 = ms2.setup("p", 0)
    sh2.recover_index()
    parts = sh2.lookup_partitions([], 0, 10**15).parts_by_schema["gauge"]
    # query only the first chunk's window: 30 samples @10s
    qs, qe = start_ms, start_ms + 29 * 10_000
    assert sh2.ensure_paged(parts, qs, qe) == 2 * 30
    # widening the end pages the next span via upper (page-only) paging
    assert sh2.ensure_paged(parts, qs, qe + 300_000) == 2 * 30
    # repeat is a no-op (coverage cached)
    assert sh2.ensure_paged(parts, qs, qe + 300_000) == 0
    _, _, counts, _ = sh2.gather_series(parts)
    assert counts.tolist() == [60, 60]


def test_odp_live_row_narrow_then_wide_query(tmp_path):
    """A narrow historical query on a LIVE row must not poison coverage for a
    later wider query: lower paging always reaches the in-memory floor so the
    resident region stays contiguous."""
    cs = LocalDiskColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(column_store=cs,
                            meta_store=LocalDiskMetaStore(str(tmp_path)))
    shard = ms.setup("p", 0)
    start_ms = 1_000_000
    stream = list(batch_stream(gauge_batch(1, 60, start_ms=start_ms),
                               samples_per_chunk=20))
    for b, off in stream:
        shard.ingest(b, off)
    shard.flush_all_groups()
    store = shard.stores["gauge"]
    store.evict_oldest(30)                 # first 30 samples now disk-only
    parts = shard.lookup_partitions([], 0, 10**15).parts_by_schema["gauge"]
    # narrow query over just the first 10 evicted samples
    shard.ensure_paged(parts, start_ms, start_ms + 9 * 10_000)
    # wide query over everything: all 60 samples must be resident
    shard.ensure_paged(parts, start_ms, 10**15)
    _, _, counts, _ = shard.gather_series(parts)
    assert counts.tolist() == [60]
    ts_row = store.ts[parts[0].row, :60]
    assert (np.diff(ts_row) == 10_000).all()   # contiguous, no gaps


def test_odp_eviction_invalidates_coverage(tmp_path):
    """If paged-in history is evicted, the coverage cache must not claim it is
    still resident — a repeat query re-pages from disk."""
    cs = LocalDiskColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(column_store=cs,
                            meta_store=LocalDiskMetaStore(str(tmp_path)))
    shard = ms.setup("p", 0)
    start_ms = 1_000_000
    stream = list(batch_stream(gauge_batch(2, 60, start_ms=start_ms),
                               samples_per_chunk=30))
    for b, off in stream:
        shard.ingest(b, off)
    shard.flush_all_groups()
    cs.close()

    ms2 = TimeSeriesMemStore(column_store=LocalDiskColumnStore(str(tmp_path)),
                             meta_store=LocalDiskMetaStore(str(tmp_path)))
    sh2 = ms2.setup("p", 0)
    sh2.recover_index()
    parts = sh2.lookup_partitions([], 0, 10**15).parts_by_schema["gauge"]
    assert sh2.ensure_paged(parts, 0, 10**15) == 120
    store = sh2.stores["gauge"]
    store.evict_oldest(30)          # drop the oldest 30 samples per series
    assert store.paged_floor[parts[0].row] == np.iinfo(np.int64).max
    assert sh2.ensure_paged(parts, start_ms, 10**15) == 60  # re-paged
    _, _, counts, _ = sh2.gather_series(parts)
    assert counts.tolist() == [60, 60]


def test_bench_persist_smoke():
    """The persist bench workload runs and emits JSON lines."""
    import io
    import json
    from contextlib import redirect_stdout

    from bench.suite import bench_persist
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench_persist(quick=True)
    lines = [json.loads(ln) for ln in buf.getvalue().strip().splitlines()]
    assert {ln["metric"] for ln in lines} == {
        "flush_samples_per_sec", "read_samples_per_sec"}
    assert all(ln["value"] > 0 for ln in lines)
