"""Write-path distributed tracing, freshness SLOs, and trace-ring
satellites (ISSUE 12; doc/observability.md write-path sections).

Covers: W3C traceparent accept/mint at the doors, the write-path span
tree (door -> WAL append -> fsync wait -> replication fan-out ->
replica WAL/ingest) stitched into ONE trace over real sockets, the
ingest slowlog, the freshness histograms + sustained-breach health
fold, trace-ring eviction (410-gone vs 404) and the /admin/traces
limit/origin filters.
"""
import json
import tempfile
import time

import numpy as np
import pytest

from filodb_tpu.config import FilodbSettings
from filodb_tpu.http import remotepb
from filodb_tpu.standalone import DatasetConfig, FiloServer
from filodb_tpu.utils import snappy as fsnappy
from filodb_tpu.utils.metrics import (TraceCollector, collector,
                                      make_traceparent, mint_trace_id,
                                      parse_traceparent, registry)


def _write_payload(series=6, k=3, ws="trc", start_ms=None):
    start = start_ms or (int(time.time() * 1000) - 60_000)
    out = []
    for i in range(series):
        labels = [("__name__", "trace_test_total"), ("_ws_", ws),
                  ("_ns_", "t"), ("inst", f"i{i}")]
        samples = [(float(i + j), start + j * 10_000) for j in range(k)]
        out.append(remotepb.PromTimeSeries(labels, samples))
    return fsnappy.compress(remotepb.encode_write_request(out))


@pytest.fixture
def server():
    srv = FiloServer(datasets=[DatasetConfig("prometheus", num_shards=2)])
    yield srv
    srv.shutdown()


# ------------------------------------------------------ traceparent


def test_traceparent_parse_and_mint():
    tid = mint_trace_id()
    assert len(tid) == 32 and int(tid, 16) >= 0
    hdr = make_traceparent(tid)
    assert parse_traceparent(hdr) == tid
    # malformed / invalid headers are rejected, not crashed on
    assert parse_traceparent(None) is None
    assert parse_traceparent("") is None
    assert parse_traceparent("garbage") is None
    assert parse_traceparent("00-" + "0" * 32 + "-" + "1" * 16 + "-01") \
        is None                                  # all-zero trace id
    assert parse_traceparent("00-" + "a" * 32 + "-" + "0" * 16 + "-01") \
        is None                                  # all-zero span id
    assert parse_traceparent("ff-" + "a" * 32 + "-" + "b" * 16 + "-01") \
        is None                                  # forbidden version
    # non-32-hex internal ids are hashed into shape, not emitted raw
    weird = make_traceparent("not-hex!")
    assert parse_traceparent(weird)


# ------------------------------------------------- door span trees


def test_remote_write_minted_trace_and_span_tree(server):
    st, pay = server.api.handle("POST", "/api/v1/write", {},
                                _write_payload())
    assert st == 204
    hdrs = pay["_headers"]
    tid = hdrs["X-Trace-Id"]
    assert parse_traceparent(hdrs["traceparent"]) == tid
    leaves = {e["span"].rsplit(".", 1)[-1] for e in collector.trace(tid)}
    assert {"remote_write", "rw_decode", "rw_admission",
            "rw_build_slabs", "ingest_columns"} <= leaves
    # the trace is listed under the remote_write origin
    st, listing = server.api.handle("GET", "/admin/traces",
                                    {"origin": "remote_write"}, b"")
    assert st == 200 and tid in listing["data"]
    # and served back as one tree
    st, tree = server.api.handle("GET", f"/admin/traces/{tid}", {}, b"")
    assert st == 200 and tree["data"]["traceID"] == tid


def test_remote_write_accepts_client_traceparent(server):
    tid = mint_trace_id()
    st, pay = server.api.handle(
        "POST", "/api/v1/write", {}, _write_payload(),
        headers={"Traceparent": make_traceparent(tid)})
    assert st == 204
    assert pay["_headers"]["X-Trace-Id"] == tid
    assert collector.trace(tid), "client trace id must carry the spans"


def test_rejected_payload_still_carries_trace_headers(server):
    """The documented contract: EVERY response — a 400 included —
    answers with its trace headers so the operator can correlate."""
    tid = mint_trace_id()
    st, pay = server.api.handle(
        "POST", "/api/v1/write", {}, b"\x00garbled",
        headers={"traceparent": make_traceparent(tid)})
    assert st == 400 and pay["errorType"] == "bad_data"
    assert pay["_headers"]["X-Trace-Id"] == tid
    assert parse_traceparent(pay["_headers"]["traceparent"]) == tid


def test_influx_door_traceparent(server):
    tid = mint_trace_id()
    st, pay = server.api.handle(
        "POST", "/influx/write", {},
        b"m,_ws_=trc,_ns_=t,inst=a value=1.0\n",
        headers={"traceparent": make_traceparent(tid)})
    assert st == 204
    assert pay["_headers"]["X-Trace-Id"] == tid
    leaves = {e["span"].rsplit(".", 1)[-1] for e in collector.trace(tid)}
    assert "influx_write" in leaves


# ----------------------------------------- stitched RF-2 write trace


def test_replicated_write_stitches_one_trace(tmp_path):
    """An RF-2 write through real replication sockets produces ONE
    trace: door + WAL + fan-out spans locally, the replica's WAL append
    / commit wait / ingest spans shipped back in the ack."""
    cfg = FilodbSettings()
    cfg.wal.enabled = True
    cfg.wal.dir = str(tmp_path / "walA")
    cfg.replication.enabled = True
    cfg.replication.factor = 2
    cfg.replication.ack_mode = "quorum"
    # the replica: a bare memstore + WAL behind a replication door
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.replication import ReplicationServer
    from filodb_tpu.wal import WalManager
    ms_b = TimeSeriesMemStore()
    wal_b = WalManager(str(tmp_path / "walB"), "prometheus")
    door_b = ReplicationServer(ms_b, node="B",
                               wals={"prometheus": wal_b}).start()
    srv = None
    try:
        srv = FiloServer(
            datasets=[DatasetConfig("prometheus", num_shards=1)],
            config=cfg, node_name="A",
            replication_peers={"B": ("127.0.0.1", door_b.address[1])})
        tid = mint_trace_id()
        st, pay = srv.api.handle(
            "POST", "/api/v1/write", {}, _write_payload(),
            headers={"traceparent": make_traceparent(tid)})
        assert st == 204 and pay["_headers"]["X-Trace-Id"] == tid
        leaves = {e["span"].rsplit(".", 1)[-1]
                  for e in collector.trace(tid)}
        assert {"remote_write", "wal_append", "wal_commit_wait",
                "replication_fanout", "replica_append",
                "ingest_columns"} <= leaves, leaves
        # the replica actually ingested under the same trace: its copy
        # holds the samples
        assert ms_b.get_shard("prometheus", 0).stats.rows_ingested > 0
    finally:
        if srv is not None:
            srv.shutdown()
        door_b.stop()
        wal_b.close()


# ------------------------------------------- ingest slowlog + freshness


def test_ingestlog_records_slow_batches_with_breakdown(server):
    from filodb_tpu.utils.slowlog import ingestlog
    ingestlog.clear()
    server.api._config.ingest.slow_batch_threshold_s = 1e-9
    try:
        st, pay = server.api.handle("POST", "/api/v1/write", {},
                                    _write_payload(ws="slowws"))
        assert st == 204
        st, il = server.api.handle("GET", "/admin/ingestlog", {}, b"")
        assert st == 200
        recs = il["data"]["entries"]
        assert recs, "a sub-ns threshold must record every batch"
        rec = recs[-1]
        assert rec["origin"] == "remote_write"
        assert rec["tenant"]["ws"] == "slowws"
        assert rec["samples"] == 18 and rec["series"] == 6
        assert rec["bytes_in"] > 0 and rec["shards"]
        assert rec["trace_id"] == pay["_headers"]["X-Trace-Id"]
        for stage in ("decode_s", "admission_s", "build_slabs_s",
                      "wal_append_s", "wal_commit_wait_s",
                      "replication_s", "ingest_s"):
            assert stage in rec["stages"]
        assert rec["spans"], "span tree copied at record time"
        # clear empties the ring
        st, cleared = server.api.handle("POST", "/admin/ingestlog/clear",
                                        {}, b"")
        assert st == 200 and cleared["data"]["cleared"] >= 1
        assert server.api.handle("GET", "/admin/ingestlog",
                                 {}, b"")[1]["data"]["count"] == 0
    finally:
        server.api._config.ingest.slow_batch_threshold_s = 5.0


def test_freshness_histograms_and_sustained_breach_health(server):
    from filodb_tpu.utils.freshness import freshness
    freshness.reset()
    freshness.configure(threshold_s=1e-9, breach_count=3, window_s=60.0)
    try:
        now = int(time.time() * 1000)
        for _ in range(2):
            st, _ = server.api.handle(
                "POST", "/api/v1/write", {},
                _write_payload(ws="fresh", start_ms=now - 30_000))
            assert st == 204
        ack = registry.histogram("ingest_ack_seconds", ws="fresh",
                                 origin="remote_write")
        fresh = registry.histogram("ingest_freshness_seconds", ws="fresh")
        assert ack.count >= 2 and fresh.count >= 2
        # freshness = ack wall clock minus newest sample ts (~10 s here:
        # the payload's newest stamp is start + 2*10 s = now - 10 s)
        assert 1.0 < fresh.max < 120.0
        # 2 breaches < breach_count: still ok
        assert freshness.verdict()["status"] == "ok"
        st, _ = server.api.handle("POST", "/api/v1/write", {},
                                  _write_payload(ws="fresh"))
        v = freshness.verdict()
        assert v["status"] == "degraded" and v["recentBreaches"] >= 3
        # the health tree folds it in
        h = server.api.handle("GET", "/api/v1/status/health",
                              {}, b"")[1]["data"]
        assert h["subsystems"]["ingest"]["status"] == "degraded"
        assert h["status"] != "ok"
        # breaches age out -> self-clears
        freshness.configure(window_s=0.05)
        time.sleep(0.1)
        assert freshness.verdict()["status"] == "ok"
    finally:
        freshness.reset()
        freshness.configure(threshold_s=5.0, breach_count=3,
                            window_s=60.0)


def test_injected_fsync_delay_visible_everywhere(tmp_path):
    """The acceptance drill in unit form: a wal.fsync delay surfaces in
    the fsync histogram, the ingest slowlog, the freshness histograms,
    and the health verdict."""
    from filodb_tpu.utils.faults import faults
    from filodb_tpu.utils.freshness import freshness
    from filodb_tpu.utils.slowlog import ingestlog
    cfg = FilodbSettings()
    cfg.wal.enabled = True
    cfg.wal.dir = str(tmp_path / "wal")
    cfg.ingest.slow_batch_threshold_s = 0.02
    cfg.ingest.freshness_breach_count = 2
    freshness.reset()
    ingestlog.clear()
    srv = FiloServer(datasets=[DatasetConfig("prometheus",
                                             num_shards=1)], config=cfg)
    try:
        delay = 0.1
        with faults.plan("wal.fsync", "delay", first_k=4,
                         delay_s=delay):
            for _ in range(2):
                st, _ = srv.api.handle("POST", "/api/v1/write", {},
                                       _write_payload(ws="fault"))
                assert st == 204
        assert registry.histogram(
            "wal_fsync_seconds",
            dataset="prometheus").max >= delay * 0.8
        recs = [r for r in ingestlog.entries()
                if r["stages"]["wal_commit_wait_s"] >= delay * 0.5]
        assert recs, "the slow batches must carry the fsync wait"
        assert registry.histogram("ingest_ack_seconds", ws="fault",
                                  origin="remote_write").max \
            >= delay * 0.8
        h = srv.api.handle("GET", "/api/v1/status/health",
                           {}, b"")[1]["data"]
        assert h["subsystems"]["ingest"]["status"] == "degraded"
    finally:
        srv.shutdown()
        freshness.reset()
        freshness.configure(threshold_s=5.0, breach_count=3,
                            window_s=60.0)


def test_openmetrics_route_carries_ingest_exemplar(server):
    """The acceptance criterion end to end: after a traced write,
    /metrics?format=openmetrics serves an exemplar on an ingest latency
    histogram under the OpenMetrics content type, while plain /metrics
    stays exemplar- and metadata-free."""
    st, pay = server.api.handle("POST", "/api/v1/write", {},
                                _write_payload(ws="omws"))
    assert st == 204
    tid = pay["_headers"]["X-Trace-Id"]
    st, om = server.api.handle("GET", "/metrics",
                               {"format": "openmetrics"}, b"")
    assert st == 200
    assert om.content_type.startswith("application/openmetrics-text")
    assert om.endswith("# EOF\n")
    ex_lines = [ln for ln in om.splitlines()
                if ln.startswith("ingest_ack_seconds_bucket")
                and f'# {{trace_id="{tid}"}}' in ln]
    assert ex_lines, "ingest latency histogram must carry the exemplar"
    st, plain = server.api.handle("GET", "/metrics", {}, b"")
    assert "# " not in plain and "trace_id=" not in plain
    # unknown formats are a clean 400
    st, _ = server.api.handle("GET", "/metrics", {"format": "bogus"},
                              b"")
    assert st == 400


# -------------------------------------------- trace-ring satellites


def test_trace_collector_eviction_ring_and_counter():
    c = TraceCollector(max_traces=3, max_events=8)
    before = registry.counter("trace_evictions").value
    for i in range(5):
        c.record(f"t{i}", {"span": "s", "dur_s": 0.0})
    assert c.trace_ids() == ["t2", "t3", "t4"]
    assert c.was_evicted("t0") and c.was_evicted("t1")
    assert not c.was_evicted("t3")
    assert not c.was_evicted("never-seen")
    assert registry.counter("trace_evictions").value == before + 2
    # a re-recorded evicted id is live again
    c.record("t0", {"span": "s", "dur_s": 0.0})
    assert not c.was_evicted("t0")
    # ...and a RE-eviction refreshes its ring slot instead of
    # duplicating it: rotate the evicted ring fully (maxlen is
    # 4*max_traces here, floored at 64) and t0 must still answer
    # evicted (a deque duplicate would let the rotation discard the
    # set entry early -> 404 where 410 was promised)
    for t in ("tx", "ty", "tz"):          # t3, t4, then t0 evict again
        c.record(t, {"span": "s", "dur_s": 0.0})
    assert c.was_evicted("t0")
    for i in range(c._evicted.maxlen):
        c.record(f"fill{i}a", {"span": "s", "dur_s": 0.0})
        c.record(f"fill{i}b", {"span": "s", "dur_s": 0.0})
    assert len(c._evicted) == len(c._evicted_set) == c._evicted.maxlen
    # origins evict alongside their traces
    c.note_origin("t3", "query")
    assert c.trace_ids(origin="query") == ["t3"]
    for i in range(10, 14):
        c.record(f"t{i}", {"span": "s", "dur_s": 0.0})
    assert c.trace_ids(origin="query") == []


def test_traces_route_410_gone_vs_404(monkeypatch, server):
    from filodb_tpu.utils import metrics as m
    small = TraceCollector(max_traces=2, max_events=8)
    monkeypatch.setattr(m, "collector", small)
    for i in range(4):
        small.record(f"tr{i}", {"span": "s", "dur_s": 0.0,
                                "end_unix_s": i})
    st, _ = server.api.handle("GET", "/admin/traces/tr3", {}, b"")
    assert st == 200
    st, pay = server.api.handle("GET", "/admin/traces/tr0", {}, b"")
    assert st == 410 and pay["errorType"] == "gone"
    st, _ = server.api.handle("GET", "/admin/traces/nope", {}, b"")
    assert st == 404


def test_traces_list_limit_and_origin_filters(monkeypatch, server):
    from filodb_tpu.utils import metrics as m
    c = TraceCollector(max_traces=32, max_events=8)
    monkeypatch.setattr(m, "collector", c)
    for i in range(6):
        c.record(f"q{i}", {"span": "s", "dur_s": 0.0})
        c.note_origin(f"q{i}", "query")
    c.record("w0", {"span": "s", "dur_s": 0.0})
    c.note_origin("w0", "remote_write")
    c.record("r0", {"span": "s", "dur_s": 0.0})
    c.note_origin("r0", "rule_eval")
    st, pay = server.api.handle("GET", "/admin/traces", {"limit": "3"},
                                b"")
    assert st == 200 and pay["data"] == ["q5", "w0", "r0"]
    st, pay = server.api.handle("GET", "/admin/traces",
                                {"origin": "query", "limit": "2"}, b"")
    assert st == 200 and pay["data"] == ["q4", "q5"]
    st, pay = server.api.handle("GET", "/admin/traces",
                                {"origin": "rule_eval"}, b"")
    assert st == 200 and pay["data"] == ["r0"]
    st, _ = server.api.handle("GET", "/admin/traces",
                              {"origin": "bogus"}, b"")
    assert st == 400


def test_query_traces_tagged_with_query_origin(server):
    sh = server.memstore.get_shard("prometheus", 0)
    from filodb_tpu.ingest.generator import gauge_batch
    START = 1_600_000_000_000
    sh.ingest(gauge_batch(4, 30, start_ms=START))
    st, pay = server.api.handle(
        "GET", "/api/v1/query_range",
        {"query": "sum(heap_usage)", "start": str(START // 1000 + 60),
         "end": str(START // 1000 + 300), "step": "60"}, b"")
    assert st == 200 and pay.get("traceID")
    ids = server.api.handle("GET", "/admin/traces",
                            {"origin": "query"}, b"")[1]["data"]
    assert pay["traceID"] in ids


# --------------------------------------------------- replica lag age


def test_replica_lag_seconds_tracks_behind_age():
    from filodb_tpu.replication.replicator import _PeerState

    class _DeadClient:
        def append_record(self, *a, **k):
            raise ConnectionError("dead")

    st = _PeerState("peer1", _DeadClient(), "lagds", lag_threshold=4,
                    queue_max=8)
    g = registry.gauge("replica_lag_seconds", dataset="lagds",
                       peer="peer1")
    st.note_failure("dead")
    assert st.behind_since > 0
    assert st.snapshot()["lagSeconds"] >= 0.0
    time.sleep(0.05)
    st.note_failure("dead")
    assert g.value >= 0.05
    # repair clears both the debt and the age
    st.note_repaired()
    assert st.behind_since == 0.0 and g.value == 0.0
    assert st.snapshot()["lagSeconds"] == 0.0
