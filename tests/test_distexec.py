"""Aggregation pushdown + streaming distributed execution (PR 15).

Covers the ISSUE-15 contract: node-level reduce pushdown is bit-
identical to the ship-everything baseline across dense/ragged/histogram
aggregations, unreachable nodes fall back to the per-shard (failover)
path, duplicate-shard gather dedup keeps working on partials, streamed
multi-frame replies round-trip with CRC framing, and a torn stream is a
typed remote_failure — never a hang, never a partial passed off as
full."""
import socket
import struct
import threading
import zlib

import numpy as np
import pytest

from filodb_tpu.core.index import Equals
from filodb_tpu.ingest.generator import (counter_batch, gauge_batch,
                                         histogram_batch)
from filodb_tpu.parallel import serialize, streams
from filodb_tpu.parallel import transport as tr
from filodb_tpu.parallel.shardmapper import SpreadProvider
from filodb_tpu.parallel.testcluster import make_fanout_cluster
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.exec import (AggPartial, AggregateMapReduce,
                                   DistConcatExec, MultiSchemaPartitionsExec,
                                   PeriodicSamplesMapper, RawBlock,
                                   ReduceAggregateExec, RemoteAggregateExec,
                                   StitchRvsExec)
from filodb_tpu.query.execbase import QueryError
from filodb_tpu.query.pushdown import (PUSHABLE_OPS, PushdownDispatcher,
                                       plan_aggregate_pushdown)
from filodb_tpu.query.rangevector import (PlannerParams, QueryContext,
                                          RangeVectorKey, ResultBlock)

START = 1_600_000_020_000
S = START // 1000


@pytest.fixture(scope="module")
def cluster():
    """4 data nodes x 2 shards each, coordinator with remote dispatchers
    — the ISSUE-15 fan-out shape.  `int_gauge` carries integer samples:
    every partial-sum component is then exactly representable, so the
    bitwise on/off contract holds regardless of how the merge tree
    associates (float data only guarantees last-ulp equality when a
    group's series cross shard boundaries)."""
    int_gauge = gauge_batch(192, 180, start_ms=START, metric="int_gauge")
    int_gauge.columns["value"] = np.floor(int_gauge.columns["value"])
    c = make_fanout_cluster(
        [gauge_batch(192, 180, start_ms=START), int_gauge,
         counter_batch(64, 180, start_ms=START),
         histogram_batch(48, 180, start_ms=START)],
        num_shards=8, nodes=("n1", "n2", "n3", "n4"), with_truth=True)
    truth = QueryEngine("prometheus", c.truth, c.mapper,
                        SpreadProvider(default_spread=1))
    yield c, truth
    c.stop()


def _as_map(res):
    out = {}
    for b in res.blocks:
        vals = np.asarray(b.values)
        for i, k in enumerate(b.keys):
            out[k] = (tuple(np.asarray(b.wends).tolist()),
                      vals[i].tobytes())
    return out


def _range(eng, q, **kw):
    pp = PlannerParams(**kw) if kw else None
    return eng.query_range(q, S + 600, 60, S + 3600, pp)


# ------------------------------------------------- pushdown A/B identity


@pytest.mark.parametrize("q", [
    'sum by (_ns_)(heap_usage)',                    # dense gauge
    'sum by (dc)(int_gauge)',                       # cross-shard groups
    'avg by (dc)(int_gauge)',
    'stddev by (dc)(int_gauge)',
    'min(heap_usage)',
    'max by (_ns_)(heap_usage)',
    'count by (_ns_)(heap_usage)',
    'group by (dc)(heap_usage)',
    'sum by (_ns_)(rate(request_total[5m]))',       # counter + range fn
    'sum by (_ns_)(http_latency)',                  # histogram [G, W, B]
])
def test_pushdown_on_off_bit_identical(cluster, q):
    c, truth = cluster
    on = _range(c.engine, q, aggregation_pushdown=True)
    off = _range(c.engine, q, aggregation_pushdown=False)
    want = _range(truth, q)
    assert on.error is None and off.error is None and want.error is None
    assert on.num_series > 0                    # never vacuously equal
    assert on.stats.pushdown_pushed >= 2        # >= 2 node groups engaged
    assert off.stats.pushdown_pushed == 0
    assert _as_map(on) == _as_map(off)
    # same association order as the single-store truth engine (shard
    # partials merge in shard order both ways at this integer scale)
    assert _as_map(on) == _as_map(want)
    # ship-everything moves strictly more wire bytes than the pushed path
    assert off.stats.wire_bytes > on.stats.wire_bytes


def test_pushdown_ragged_identical(cluster):
    """Series born mid-range (NaN holes) aggregate identically."""
    c, truth = cluster
    q = 'sum by (_ns_)(heap_usage offset 10m)'
    on = _range(c.engine, q, aggregation_pushdown=True)
    off = _range(c.engine, q, aggregation_pushdown=False)
    assert on.error is None and off.error is None
    assert _as_map(on) == _as_map(off) == _as_map(_range(truth, q))


def test_non_pushable_shapes_keep_per_shard_path(cluster):
    c, _ = cluster
    # ship-raw children carry no map-phase transformer, which breaks the
    # pushable transformer chain — the aggregation stays on the
    # per-shard path even with pushdown enabled
    res = _range(c.engine, 'sum by (_ns_)(heap_usage)',
                 aggregation_pushdown=True, ship_raw_series=True)
    assert res.error is None
    assert res.stats.pushdown_pushed == 0
    assert res.stats.pushdown_not_pushable >= 8     # one per remote shard
    # stats surface the verdicts in the wire shape
    d = res.stats.to_dict()
    assert d["pushdown"]["notPushable"] >= 8
    assert d["wireBytes"] > 0


@pytest.mark.parametrize("q", [
    'topk(3, heap_usage)',
    'bottomk(2, heap_usage)',
    'quantile(0.9, heap_usage)',
    'quantile by (_ns_)(0.5, int_gauge)',
    'count_values("v", int_gauge)',
])
def test_rank_aggregations_push_bit_identical(cluster, q):
    """PR 17: topk/bottomk/quantile/count_values report `pushed` (not
    `notPushable`) and stay bit-identical to the ship-everything path
    and the single-store truth engine."""
    c, truth = cluster
    on = _range(c.engine, q, aggregation_pushdown=True)
    off = _range(c.engine, q, aggregation_pushdown=False)
    want = _range(truth, q)
    assert on.error is None and off.error is None and want.error is None
    assert on.num_series > 0
    assert on.stats.pushdown_pushed >= 2
    assert on.stats.pushdown_not_pushable == 0
    assert off.stats.pushdown_pushed == 0
    assert _as_map(on) == _as_map(off)
    assert _as_map(on) == _as_map(want)


def test_pushdown_stats_and_wire_attribution(cluster):
    c, _ = cluster
    res = _range(c.engine, 'sum by (_ns_)(heap_usage)')
    assert res.error is None
    d = res.stats.to_dict()
    assert d["pushdown"]["pushed"] == 4             # one group per node
    assert d["wireBytes"] > 0
    # wire bytes are a SUBSET of bytes_transferred (which also counts
    # host->device uploads)
    assert res.stats.wire_bytes <= res.stats.bytes_transferred


# ------------------------------------------------- dedup + fallback


def _leaf(ctx, shard, with_agg=True):
    leaf = MultiSchemaPartitionsExec(
        ctx, "prometheus", shard, [Equals("_metric_", "heap_usage")],
        START, START + 3_600_000)
    leaf.add_transformer(PeriodicSamplesMapper(
        START + 600_000, 60_000, START + 3_600_000, None, None, ()))
    if with_agg:
        leaf.add_transformer(AggregateMapReduce("sum", (), ("_ns_",), ()))
    return leaf


def test_duplicate_shards_never_grouped(cluster):
    """Both owners of a shard materialized (live-handoff window): the
    twins stay DIRECT children so the PR-11 gather dedup contract keeps
    holding on partials."""
    c, _ = cluster
    ctx = QueryContext()
    disp = list(c.servers.values())[0]
    rd = tr.RemoteNodeDispatcher(*disp.address)
    kids = [_leaf(ctx, 0), _leaf(ctx, 0), _leaf(ctx, 1)]
    for k in kids:
        k.dispatcher = rd
    out, _ = plan_aggregate_pushdown(kids, "sum", (), ctx)
    dups = [p for p in out if isinstance(p, MultiSchemaPartitionsExec)]
    groups = [p for p in out if isinstance(p, RemoteAggregateExec)]
    assert len(dups) == 2 and all(p.shard == 0 for p in dups)
    assert len(groups) == 1 and [k.shard for k in groups[0].children] == [1]


def test_dedup_on_partials_no_double_count(cluster):
    """A shard listed twice contributes EXACTLY once to the aggregate —
    executed end to end against a real node."""
    c, truth = cluster
    ctx = QueryContext()
    node = c.owner[0]
    rd = tr.RemoteNodeDispatcher(*c.servers[node].address)
    kids = [_leaf(ctx, 0), _leaf(ctx, 0)]
    for k in kids:
        k.dispatcher = rd
    plan = ReduceAggregateExec(ctx, kids, "sum")
    from filodb_tpu.query.exec import AggregatePresenter
    plan.add_transformer(AggregatePresenter("sum", ()))
    res = plan.execute(None)
    assert res.error is None
    single = ReduceAggregateExec(ctx, [_leaf(QueryContext(), 0)], "sum")
    single.children[0].dispatcher = rd
    single.add_transformer(AggregatePresenter("sum", ()))
    want = single.execute(None)
    assert _as_map(res) == _as_map(want)


def test_fallback_when_node_group_unreachable(cluster):
    """PushdownDispatcher: dead node -> the group degrades to the
    per-shard path (here: leaves with live per-shard dispatchers on a
    DIFFERENT address), counted as a fallback verdict."""
    c, _ = cluster
    ctx = QueryContext()
    # dead target: a fresh unused port
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_addr = s.getsockname()
    s.close()
    live = tr.RemoteNodeDispatcher(*c.servers[c.owner[0]].address)
    kids = [_leaf(ctx, 0), _leaf(ctx, 1)]
    for k in kids:
        k.dispatcher = live                   # per-shard fallback target
    group = RemoteAggregateExec(ctx, kids, "sum", ())
    group.dispatcher = PushdownDispatcher(
        tr.RemoteNodeDispatcher(*dead_addr, timeout_s=0.5))
    data, stats = group.dispatcher.dispatch(group, None)
    assert isinstance(data, AggPartial)
    assert stats.pushdown_fallback == 1 and stats.pushdown_pushed == 0


# ------------------------------------------------- wire round-trips


def test_remote_aggregate_subtree_roundtrip():
    ctx = QueryContext(query_id="pd1")
    kids = [_leaf(ctx, 0), _leaf(ctx, 1)]
    plan = RemoteAggregateExec(ctx, kids, "sum", ())
    plan2 = serialize.loads(serialize.dumps(plan))
    assert isinstance(plan2, RemoteAggregateExec)
    assert plan2.print_tree() == plan.print_tree()
    from filodb_tpu.query.execbase import InProcessPlanDispatcher
    assert all(isinstance(k.dispatcher, InProcessPlanDispatcher)
               for k in plan2.children)


def test_kill_token_reaches_pushed_leaves():
    """serialize gives every exec node its own QueryContext; the data-
    node registration must stamp the kill token on every LEAF of a
    pushed group — the leaves' exec-boundary cancel checks are what
    actually stop the scans."""
    ctx = QueryContext(query_id="kt1")
    plan = RemoteAggregateExec(ctx, [_leaf(ctx, 0), _leaf(ctx, 1)],
                               "sum", ())
    plan2 = serialize.loads(serialize.dumps(plan))

    class _Ent:
        token = object()

    ent = _Ent()
    tr._attach_registration(plan2, ent)
    assert plan2.ctx.cancel is ent.token
    assert plan2.children                       # non-vacuous
    for k in plan2.children:
        assert k.ctx.cancel is ent.token


def test_nonleaf_concat_still_refuses():
    with pytest.raises(serialize.NotSerializable):
        serialize.dumps(DistConcatExec(QueryContext(), []))


def test_hist_rawblock_scheme_drift_roundtrip_and_concat():
    """Histogram RawBlocks from two shards with DIFFERENT bucket schemes
    survive the wire and rebucket onto the union at concat."""
    rng = np.random.default_rng(7)
    les_a = np.array([1.0, 2.0, 4.0, np.inf])
    les_b = np.array([1.0, 4.0, 8.0, np.inf])

    def mk(les, base_val):
        counts = np.cumsum(
            rng.integers(0, 3, size=(2, 5, len(les))), axis=2).astype(float)
        counts += base_val
        return RawBlock(
            [RangeVectorKey.make({"inst": f"i{base_val}-{j}"})
             for j in range(2)],
            np.tile(np.arange(5, dtype=np.int32) * 1000, (2, 1)),
            counts, START, bucket_les=les, samples=10)

    ra, rb = mk(les_a, 0), mk(les_b, 100)
    ra2 = serialize.loads(serialize.dumps(ra))
    np.testing.assert_array_equal(ra2.bucket_les, les_a)
    np.testing.assert_array_equal(np.asarray(ra2.values),
                                  np.asarray(ra.values))
    out = DistConcatExec(QueryContext(), []).compose(
        [serialize.loads(serialize.dumps(r)) for r in (ra, rb)], None)
    assert isinstance(out, RawBlock)
    np.testing.assert_array_equal(out.bucket_les,
                                  np.array([1.0, 2.0, 4.0, 8.0, np.inf]))
    assert np.asarray(out.values).shape == (4, 5, 5)


def test_agg_partial_sketch_roundtrip():
    keys = [RangeVectorKey.make({"g": "x"})]
    wends = np.asarray([1000, 2000], dtype=np.int64)
    sk = np.zeros((1, 2, 4, 2))
    sk[..., 0] = np.nan
    p = AggPartial("quantile", keys, wends, sketch=sk, params=(0.5,))
    p2 = serialize.loads(serialize.dumps(p))
    np.testing.assert_array_equal(p2.sketch, sk)
    assert p2.params == (0.5,)


# ------------------------------------------------- stream split/assemble


def _assemble(begin, pieces):
    asm = streams.StreamAssembler(begin)
    for p in pieces:
        asm.add(p)
    return asm.finish()


def test_split_assemble_rawblock_roundtrip():
    rng = np.random.default_rng(0)
    Srows = 64
    blk = RawBlock(
        [RangeVectorKey.make({"i": str(i)}) for i in range(Srows)],
        rng.integers(0, 1000, size=(Srows, 32)).astype(np.int32),
        rng.normal(size=(Srows, 32)), START,
        samples=123, vbase=rng.normal(size=Srows), dense=False)
    split = streams.split_for_stream(blk, 4096)
    assert split is not None
    begin, pieces = split
    assert len(pieces) > 1
    out = _assemble(begin, pieces)
    assert out.keys == blk.keys
    np.testing.assert_array_equal(out.ts_off, blk.ts_off)
    np.testing.assert_array_equal(out.values, blk.values)
    np.testing.assert_array_equal(out.vbase, blk.vbase)
    assert out.samples == 123 and out.dense is False


def test_split_assemble_result_and_partial_forms():
    rng = np.random.default_rng(1)
    wends = np.arange(16, dtype=np.int64) * 1000
    rb = ResultBlock([RangeVectorKey.make({"i": str(i)}) for i in range(32)],
                     wends, rng.normal(size=(32, 16, 3)),
                     bucket_les=np.array([1.0, 2.0, np.inf]))
    begin, pieces = streams.split_for_stream(rb, 2048)
    out = _assemble(begin, pieces)
    assert out.keys == rb.keys
    np.testing.assert_array_equal(out.values, rb.values)
    np.testing.assert_array_equal(out.bucket_les, rb.bucket_les)
    # component-form partial splits over groups
    gk = [RangeVectorKey.make({"g": str(i)}) for i in range(64)]
    comp = rng.normal(size=(64, 16, 2))
    pa = AggPartial("sum", gk, wends, comp=comp)
    out = _assemble(*streams.split_for_stream(pa, 4096))
    assert out.op == "sum" and out.group_keys == gk
    np.testing.assert_array_equal(out.comp, comp)
    # candidate form splits over candidate rows, groups ride whole
    cand = AggPartial("topk", gk[:2], wends,
                      cand_keys=[RangeVectorKey.make({"i": str(i)})
                                 for i in range(64)],
                      cand_vals=rng.normal(size=(64, 16)),
                      cand_groups=rng.integers(0, 2, size=64),
                      params=(3.0,))
    out = _assemble(*streams.split_for_stream(cand, 2048))
    assert out.group_keys == gk[:2] and out.params == (3.0,)
    np.testing.assert_array_equal(out.cand_vals, cand.cand_vals)
    np.testing.assert_array_equal(out.cand_groups, cand.cand_groups)


def test_assembler_refuses_short_stream():
    rng = np.random.default_rng(2)
    blk = ResultBlock([RangeVectorKey.make({"i": str(i)}) for i in range(32)],
                      np.arange(8, dtype=np.int64), rng.normal(size=(32, 8)))
    begin, pieces = streams.split_for_stream(blk, 512)
    asm = streams.StreamAssembler(begin)
    for p in pieces[:-1]:
        asm.add(p)
    with pytest.raises(ValueError, match="short stream"):
        asm.finish()


# ------------------------------------------------- streamed dispatch e2e


def test_streamed_reply_multi_frame_identical(cluster, monkeypatch):
    """Small frames force a many-frame stream; the result is identical
    to the single-store truth and the frame count lands in stats."""
    c, truth = cluster
    from filodb_tpu.config import settings
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 4096)
    q = 'heap_usage'
    res = _range(c.engine, q)
    want = _range(truth, q)
    assert res.error is None
    assert res.stats.streamed_frames > 8
    assert _as_map(res) == _as_map(want)


def test_streamed_shipeverything_fold_identical(cluster, monkeypatch):
    """ship_raw_series (the bench strawman) + tiny frames: children ship
    full series blocks as many-frame streams and ReduceAggregateExec
    folds every slice through map+reduce as it arrives — result
    identical to the unstreamed ship-everything path AND the pushed
    path (integer data)."""
    c, truth = cluster
    from filodb_tpu.config import settings
    q = 'sum by (dc)(int_gauge)'
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 0)
    plain = _range(c.engine, q, aggregation_pushdown=False,
                   ship_raw_series=True)
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 4096)
    folded = _range(c.engine, q, aggregation_pushdown=False,
                    ship_raw_series=True)
    assert plain.error is None and folded.error is None
    assert folded.stats.streamed_frames > 8
    assert _as_map(folded) == _as_map(plain) == _as_map(_range(truth, q))


def test_fold_surfaces_group_cardinality_error(cluster, monkeypatch):
    """An application error raised INSIDE the per-frame fold (group-by
    cardinality limit) surfaces as the real error, not remote_failure."""
    c, _ = cluster
    from filodb_tpu.config import settings
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 4096)
    res = c.engine.query_range(
        'sum by (instance)(heap_usage)', S + 600, 60, S + 3600,
        PlannerParams(aggregation_pushdown=False, ship_raw_series=True,
                      group_by_cardinality_limit=2))
    assert res.error is not None
    assert "cardinality limit" in res.error
    assert "remote_failure" not in res.error


def test_fold_cardinality_limit_across_slices(cluster, monkeypatch):
    """Each row slice stays UNDER the group-by limit but the merged
    partial exceeds it: the streamed fold must still raise, exactly
    like the non-streamed compose would."""
    c, _ = cluster
    from filodb_tpu.config import settings
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 4096)
    # the limit is enforced per map invocation (per child): one shard
    # holds 24 heap_usage series = 24 groups, but a 4 KiB row slice
    # carries ~10 of them — only the merged-partial check can trip
    res = c.engine.query_range(
        'sum by (instance)(heap_usage)', S + 600, 60, S + 3600,
        PlannerParams(aggregation_pushdown=False, ship_raw_series=True,
                      group_by_cardinality_limit=20))
    assert res.error is not None
    assert "cardinality limit" in res.error
    assert "remote_failure" not in res.error


def test_stream_disabled_single_frame(cluster, monkeypatch):
    c, truth = cluster
    from filodb_tpu.config import settings
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 0)
    res = _range(c.engine, 'heap_usage')
    assert res.error is None and res.stats.streamed_frames == 0
    assert _as_map(res) == _as_map(_range(truth, 'heap_usage'))


def test_torn_stream_is_typed_remote_failure(cluster, monkeypatch):
    """The server dies mid-stream (connection severed between frames):
    the dispatch raises the typed remote_failure promptly — no hang, no
    partial block handed to the exec tree."""
    c, _ = cluster
    from filodb_tpu.config import settings
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 2048)
    real_pack = tr._pack_stream_frame
    state = {"n": 0}

    def sabotage(seq, body, last):
        state["n"] += 1
        if state["n"] == 3:
            raise ConnectionResetError("server died mid-stream")
        return real_pack(seq, body, last)

    monkeypatch.setattr(tr, "_pack_stream_frame", sabotage)
    node = c.owner[0]
    rd = tr.RemoteNodeDispatcher(*c.servers[node].address, timeout_s=5.0)
    plan = _leaf(QueryContext(query_id="torn1"), 0, with_agg=False)
    plan.dispatcher = rd
    with pytest.raises(QueryError) as ei:
        rd.dispatch(plan, None)
    assert ei.value.code == "remote_failure"
    assert "torn" in str(ei.value) or "corrupt" in str(ei.value)


def test_corrupt_stream_frame_crc_rejected(cluster, monkeypatch):
    c, _ = cluster
    from filodb_tpu.config import settings
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 2048)
    real_pack = tr._pack_stream_frame
    state = {"n": 0}

    def flip(seq, body, last):
        raw = real_pack(seq, body, last)
        state["n"] += 1
        if state["n"] == 2:          # corrupt the first piece frame body
            raw = raw[:-1] + bytes([raw[-1] ^ 0xFF])
        return raw

    monkeypatch.setattr(tr, "_pack_stream_frame", flip)
    node = c.owner[0]
    rd = tr.RemoteNodeDispatcher(*c.servers[node].address, timeout_s=5.0)
    plan = _leaf(QueryContext(query_id="crc1"), 0, with_agg=False)
    plan.dispatcher = rd
    with pytest.raises(QueryError) as ei:
        rd.dispatch(plan, None)
    assert ei.value.code == "remote_failure"
    assert "CRC" in str(ei.value)


def test_reply_serialize_failure_is_typed_error(cluster, monkeypatch):
    """A reply the server cannot serialize answers with a TYPED error
    reply on the same connection — never a torn socket that makes the
    client retry (and the node re-execute) the plan."""
    c, _ = cluster
    calls = {"n": 0}

    def boom(sock, stream_ok, plan, data, stats, spans):
        calls["n"] += 1
        raise TypeError("NotSerializable: <object at 0x0>")

    monkeypatch.setattr(tr.NodeQueryServer, "_send_reply",
                        staticmethod(boom))
    node = c.owner[0]
    rd = tr.RemoteNodeDispatcher(*c.servers[node].address, timeout_s=5.0)
    plan = _leaf(QueryContext(query_id="ser1"), 0, with_agg=False)
    plan.dispatcher = rd
    with pytest.raises(QueryError) as ei:
        rd.dispatch(plan, None)
    assert ei.value.code == "remote_failure"
    assert "NotSerializable" in str(ei.value)
    assert calls["n"] == 1                      # executed exactly once


def test_kill_mid_stream_is_structured_cancel(cluster, monkeypatch):
    """A kill landing between stream frames stops the stream with the
    typed query_canceled — the server checks the token per frame."""
    c, _ = cluster
    from filodb_tpu.config import settings
    from filodb_tpu.query.activequeries import active_queries
    monkeypatch.setattr(settings().query, "stream_frame_bytes", 2048)
    real_pack = tr._pack_stream_frame
    state = {"n": 0}

    def kill_after_first_piece(seq, body, last):
        state["n"] += 1
        if state["n"] == 3:
            active_queries.kill("killmid1", reason="admin",
                                detail="test kill mid-stream")
        return real_pack(seq, body, last)

    monkeypatch.setattr(tr, "_pack_stream_frame", kill_after_first_piece)
    node = c.owner[0]
    rd = tr.RemoteNodeDispatcher(*c.servers[node].address, timeout_s=5.0)
    plan = _leaf(QueryContext(query_id="killmid1"), 0, with_agg=False)
    plan.dispatcher = rd
    with pytest.raises(QueryError) as ei:
        rd.dispatch(plan, None)
    assert ei.value.code == "query_canceled"


# ------------------------------------------------- vectorized satellites


def test_stitch_vectorized_matches_reference():
    """StitchRvsExec.compose (searchsorted scatter) == the old per-series
    dict-of-rows loop, on ragged overlapping blocks."""
    rng = np.random.default_rng(3)

    def ref_compose(blocks):
        wends = np.unique(np.concatenate([b.wends for b in blocks]))
        merged = {}
        for b in blocks:
            pos = np.searchsorted(wends, b.wends)
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                row = merged.get(k)
                if row is None:
                    row = np.full(len(wends), np.nan)
                    merged[k] = row
                fill = vals[i]
                take = ~np.isnan(fill)
                row[pos[take]] = fill[take]
        keys = list(merged)
        return ResultBlock(keys, wends,
                           np.stack([merged[k] for k in keys]))

    def mk(keys, t0, n):
        vals = rng.normal(size=(len(keys), n))
        vals[rng.random(vals.shape) < 0.3] = np.nan
        return ResultBlock(keys, np.arange(t0, t0 + n, dtype=np.int64),
                           vals)

    ka = [RangeVectorKey.make({"i": str(i)}) for i in range(12)]
    kb = ka[6:] + [RangeVectorKey.make({"i": f"x{i}"}) for i in range(4)]
    blocks = [mk(ka, 0, 20), mk(kb, 15, 20), mk(ka[:3], 30, 10)]
    want = ref_compose(blocks)
    got = StitchRvsExec(QueryContext(), []).compose(list(blocks), None)
    assert got.keys == want.keys
    np.testing.assert_array_equal(np.asarray(got.wends),
                                  np.asarray(want.wends))
    np.testing.assert_array_equal(np.asarray(got.values),
                                  np.asarray(want.values))


def test_stitch_vectorized_histogram_blocks():
    """[S, W, B] blocks stitch bucketwise (the old loop could not)."""
    rng = np.random.default_rng(4)
    keys = [RangeVectorKey.make({"i": str(i)}) for i in range(4)]
    les = np.array([1.0, np.inf])
    a = ResultBlock(keys, np.arange(0, 8, dtype=np.int64),
                    rng.normal(size=(4, 8, 2)), bucket_les=les)
    b = ResultBlock(keys, np.arange(8, 16, dtype=np.int64),
                    rng.normal(size=(4, 8, 2)), bucket_les=les)
    out = StitchRvsExec(QueryContext(), []).compose([a, b], None)
    assert np.asarray(out.values).shape == (4, 16, 2)
    np.testing.assert_array_equal(out.values[:, :8], a.values)
    np.testing.assert_array_equal(out.values[:, 8:], b.values)
    np.testing.assert_array_equal(out.bucket_les, les)


def test_stitch_empty_first_tier_histogram():
    """An empty tier (0 series, 2-D values) arriving FIRST must not
    poison the output shape or drop the bucket scheme of a later
    histogram tier."""
    rng = np.random.default_rng(6)
    keys = [RangeVectorKey.make({"i": str(i)}) for i in range(3)]
    les = np.array([0.5, np.inf])
    empty = ResultBlock([], np.arange(0, 4, dtype=np.int64),
                        np.empty((0, 4)))
    hist = ResultBlock(keys, np.arange(4, 12, dtype=np.int64),
                       rng.normal(size=(3, 8, 2)), bucket_les=les)
    out = StitchRvsExec(QueryContext(), []).compose([empty, hist], None)
    assert np.asarray(out.values).shape == (3, 12, 2)
    np.testing.assert_array_equal(out.values[:, 4:], hist.values)
    assert np.isnan(np.asarray(out.values)[:, :4]).all()
    np.testing.assert_array_equal(out.bucket_les, les)


def test_presence_by_key_vectorized_matches_reference():
    from filodb_tpu.query.nonleaf import SetOperatorExec
    rng = np.random.default_rng(5)
    keys = [RangeVectorKey.make({"a": str(i % 3), "b": str(i % 2),
                                 "_metric_": "m"})
            for i in range(24)]
    vals = rng.normal(size=(24, 10))
    vals[rng.random(vals.shape) < 0.4] = np.nan
    block = ResultBlock(keys, np.arange(10, dtype=np.int64), vals)

    def ref(op):
        present = {}
        for i, k in enumerate(block.keys):
            mk = op._match_key(k)
            pres = ~np.isnan(vals[i])
            prev = present.get(mk, np.zeros(10, bool))
            present[mk] = prev | pres
        return present

    for kw in ({"on": ("a",)}, {"on": ("a", "b")}, {"on": ()},
               {"ignoring": ("b",)}, {}):
        op = SetOperatorExec(QueryContext(), [], [], "and", **kw)
        got = op._presence_by_key(block)
        want = ref(op)
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
