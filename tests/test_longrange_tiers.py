"""Tier-stitched planning tests: LongTimeRangePlanner's third (persisted)
tier, boundary stitching at raw-retention and latest-downsample edges —
including a range function whose lookback window straddles the split (the
known Prometheus-stitch hazard) — asserted bit-identical against a
single-tier store holding the same samples."""
import numpy as np
import pytest

from filodb_tpu.core.devicecache import ColdSegmentCache
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
from filodb_tpu.persist.compactor import SegmentCompactor
from filodb_tpu.persist.localstore import LocalDiskColumnStore
from filodb_tpu.persist.segments import PersistedTier, SegmentStore
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.exec import SelectPersistedSegmentsExec, StitchRvsExec
from filodb_tpu.query.planner import SingleClusterPlanner
from filodb_tpu.query.planners import (LongTimeRangePlanner,
                                       PersistedClusterPlanner)
from filodb_tpu.query.rangevector import QueryContext
from filodb_tpu.promql.parser import (TimeStepParams,
                                      query_range_to_logical_plan)

DS = "ltr-test"
WINDOW = 3600 * 1000
T0 = 1_600_000_000_000 - (1_600_000_000_000 % WINDOW)
INTERVAL = 60_000
N_WINDOWS = 4
NS = N_WINDOWS * WINDOW // INTERVAL
S = 6


def _grid():
    return T0 + np.arange(NS, dtype=np.int64) * INTERVAL


def _pks():
    return [PartKey("m", (("inst", f"i{i}"), ("_ws_", "w"), ("_ns_", "n")))
            for i in range(S)]


def _vals():
    # small integers: every arithmetic step is exact in f32, so hot and
    # cold paths must agree BIT-identically
    return (np.arange(S)[:, None] * 50.0 + (np.arange(NS) % 11)[None, :])


def _mapper():
    m = ShardMapper(1)
    m.update_from_event(ShardEvent("IngestionStarted", DS, 0, "n"))
    return m


class _Src:
    def __init__(self, store):
        self.store = store

    def get_shard(self, dataset, shard_num):
        return self.store.get_shard(dataset, shard_num)

    def shards_for(self, dataset):
        return self.store.shards_for(dataset)


@pytest.fixture()
def tiered(tmp_path):
    """A tiered setup: persisted segments hold ALL history; the live
    memstore holds only the last window (the working set); a separate
    single-tier reference store holds everything in memory."""
    ts_grid, pks, vals = _grid(), _pks(), _vals()
    cs = LocalDiskColumnStore(str(tmp_path))
    ms_full = TimeSeriesMemStore(column_store=cs)
    sh = ms_full.setup(DS, 0)
    sh.ingest_columns("gauge", pks, np.broadcast_to(ts_grid, (S, NS)),
                      {"value": vals})
    sh.flush_all_groups()
    seg_store = SegmentStore(str(tmp_path))
    comp = SegmentCompactor(cs, seg_store, DS, 1, window_ms=WINDOW,
                            closed_lag_ms=0)
    assert comp.compact_all(now_ms=int(ts_grid[-1]) + 10 * WINDOW) \
        == N_WINDOWS
    tier = PersistedTier(seg_store, DS, 1,
                         ColdSegmentCache(256 << 20, use_placer=False))
    # live store: last window only (the in-memory working set)
    tail_from = NS - WINDOW // INTERVAL
    ms_live = TimeSeriesMemStore()
    live = ms_live.setup(DS, 0)
    live.ingest_columns("gauge", pks,
                        np.broadcast_to(ts_grid[tail_from:],
                                        (S, NS - tail_from)),
                        {"value": vals[:, tail_from:]})
    # reference: everything in memory
    ms_ref = TimeSeriesMemStore()
    ref = ms_ref.setup(DS, 0)
    ref.ingest_columns("gauge", pks, np.broadcast_to(ts_grid, (S, NS)),
                       {"value": vals})
    mapper = _mapper()
    earliest_raw = int(ts_grid[tail_from])
    ltr = LongTimeRangePlanner(
        SingleClusterPlanner(DS, mapper), None,
        earliest_raw_time_fn=lambda: earliest_raw,
        latest_downsample_time_fn=lambda: 1 << 62,
        persisted_planner=PersistedClusterPlanner(DS, mapper, tier),
        persisted_range_fn=tier.range)
    eng_tiered = QueryEngine(DS, _Src(ms_live), mapper, planner=ltr)
    eng_ref = QueryEngine(DS, _Src(ms_ref), mapper,
                          planner=SingleClusterPlanner(DS, mapper))
    return eng_tiered, eng_ref, ts_grid, earliest_raw


def _assert_identical(res_a, res_b, q):
    assert res_a.error is None, (q, res_a.error)
    assert res_b.error is None, (q, res_b.error)
    a = {k: (w, v) for k, w, v in res_a.series()}
    b = {k: (w, v) for k, w, v in res_b.series()}
    assert set(a) == set(b), q
    for k in a:
        assert np.array_equal(a[k][0], b[k][0]), q
        va, vb = a[k][1], b[k][1]
        both_nan = np.isnan(va) & np.isnan(vb)
        assert np.array_equal(va[~both_nan], vb[~both_nan]), \
            (q, va[:8], vb[:8])


QUERIES = [
    "m",
    "sum(m)",
    "sum(rate(m[10m]))",            # lookback straddles the tier split
    "avg_over_time(m[30m])",        # wide window across the boundary
    "max by (inst) (m)",
]


@pytest.mark.parametrize("q", QUERIES)
def test_stitched_matches_single_tier(tiered, q):
    eng_tiered, eng_ref, ts_grid, earliest_raw = tiered
    start_s = int(ts_grid[0]) // 1000 + 1800
    end_s = int(ts_grid[-1]) // 1000
    res_t = eng_tiered.query_range(q, start_s, 300, end_s)
    res_r = eng_ref.query_range(q, start_s, 300, end_s)
    _assert_identical(res_t, res_r, q)
    assert res_t.stats.cold_tier in ("cold_hit", "cold_paged")


def test_query_exactly_at_raw_retention_edge(tiered):
    """Instants at the exact retention boundary: the straddle hazard —
    the raw tier serves only instants whose FULL lookback is in memory;
    the instant straddling the edge comes from the persisted tier."""
    eng_tiered, eng_ref, ts_grid, earliest_raw = tiered
    # grid aligned so one instant lands exactly on earliest_raw
    start_s = earliest_raw // 1000 - 1200
    end_s = earliest_raw // 1000 + 1200
    for q in ("sum(rate(m[10m]))", "m"):
        res_t = eng_tiered.query_range(q, start_s, 300, end_s)
        res_r = eng_ref.query_range(q, start_s, 300, end_s)
        _assert_identical(res_t, res_r, q)


def test_query_entirely_before_raw(tiered):
    eng_tiered, eng_ref, ts_grid, earliest_raw = tiered
    start_s = int(ts_grid[0]) // 1000 + 1800
    end_s = earliest_raw // 1000 - 3600
    res_t = eng_tiered.query_range("sum(rate(m[10m]))", start_s, 300, end_s)
    res_r = eng_ref.query_range("sum(rate(m[10m]))", start_s, 300, end_s)
    _assert_identical(res_t, res_r, "pre-raw")


def test_downsample_edge_with_three_tiers(tmp_path):
    """Oldest data only in downsample, middle in segments, tail in raw
    memory — one query stitches all three, identical to a single-tier
    store (downsample at the scrape resolution: periods hold exactly one
    sample, so ds values/timestamps equal raw)."""
    from filodb_tpu.downsample import (DownsampleClusterPlanner,
                                       DownsampledTimeSeriesStore,
                                       ShardDownsampler)
    ts_grid, pks, vals = _grid(), _pks(), _vals()
    res_ms = 300_000
    ts_grid = T0 + np.arange(NS, dtype=np.int64) * res_ms   # 5m scrape
    cs = LocalDiskColumnStore(str(tmp_path))
    ms_full = TimeSeriesMemStore(column_store=cs)
    sh = ms_full.setup(DS, 0)
    sh.shard_downsampler = ShardDownsampler(resolutions=(res_ms,))
    sh.ingest_columns("gauge", pks, np.broadcast_to(ts_grid, (S, NS)),
                      {"value": vals})
    sh.flush_all_groups()
    ds_store = DownsampledTimeSeriesStore(DS, column_store=cs,
                                          resolutions=(res_ms,))
    ds_store.setup_shard(0)
    ds_store.ingest_downsample_batches(
        0, sh.shard_downsampler.result_batches())
    # segments cover only the MIDDLE of history: windows [1, N)
    seg_store = SegmentStore(str(tmp_path))
    comp = SegmentCompactor(cs, seg_store, DS, 1,
                            window_ms=WINDOW * 2, closed_lag_ms=0)
    comp.compact_all(now_ms=int(ts_grid[-1]) + 100 * WINDOW)
    metas = seg_store.list(DS, 0)
    seg_store.remove(metas[0])           # oldest window: downsample-only
    tier = PersistedTier(seg_store, DS, 1,
                         ColdSegmentCache(256 << 20, use_placer=False))
    assert tier.range()[0] > int(ts_grid[0])
    # live memory: last quarter
    tail_from = 3 * NS // 4
    ms_live = TimeSeriesMemStore()
    live = ms_live.setup(DS, 0)
    live.ingest_columns("gauge", pks,
                        np.broadcast_to(ts_grid[tail_from:],
                                        (S, NS - tail_from)),
                        {"value": vals[:, tail_from:]})
    ms_ref = TimeSeriesMemStore()
    ref = ms_ref.setup(DS, 0)
    ref.ingest_columns("gauge", pks, np.broadcast_to(ts_grid, (S, NS)),
                       {"value": vals})
    mapper = _mapper()
    earliest_raw = int(ts_grid[tail_from])

    class _DsSrc(_Src):
        def get_shard(self, dataset, shard_num):
            if "::ds::" in dataset:
                return ds_store.get_shard(dataset, shard_num)
            return self.store.get_shard(dataset, shard_num)

    ltr = LongTimeRangePlanner(
        SingleClusterPlanner(DS, mapper),
        DownsampleClusterPlanner(ds_store, mapper),
        earliest_raw_time_fn=lambda: earliest_raw,
        latest_downsample_time_fn=lambda: 1 << 62,
        persisted_planner=PersistedClusterPlanner(DS, mapper, tier),
        persisted_range_fn=tier.range)
    eng_tiered = QueryEngine(DS, _DsSrc(ms_live), mapper, planner=ltr)
    eng_ref = QueryEngine(DS, _Src(ms_ref), mapper,
                          planner=SingleClusterPlanner(DS, mapper))
    start_s = int(ts_grid[0]) // 1000 + 3600
    end_s = int(ts_grid[-1]) // 1000
    for q in ("m", "sum(m)"):
        res_t = eng_tiered.query_range(q, start_s, 600, end_s)
        res_r = eng_ref.query_range(q, start_s, 600, end_s)
        _assert_identical(res_t, res_r, q)


# -------------------------------------------------- planner-level (unit)


class _RecordingPlanner:
    def __init__(self, tag):
        self.tag = tag
        self.materialized = []

    def materialize(self, plan, ctx):
        from filodb_tpu.query.exec import ExecPlan
        from filodb_tpu.query.rangevector import QueryStats

        class _D(ExecPlan):
            def __init__(self, tag, plan):
                super().__init__(QueryContext())
                self.tag, self.plan = tag, plan

            def _do_execute(self, source):
                return None, QueryStats()
        self.materialized.append(plan)
        return _D(self.tag, plan)


def _plan(q, start_s, end_s, step_s=60):
    return query_range_to_logical_plan(
        q, TimeStepParams(start_s, step_s, end_s))


def test_ltr_three_way_split_routes_and_abuts():
    start_ms = 1_600_000_000_000
    raw, ds, pers = (_RecordingPlanner("raw"), _RecordingPlanner("ds"),
                     _RecordingPlanner("pers"))
    earliest_raw = start_ms + 3 * 3600_000
    p_range = (start_ms + 3600_000, start_ms + 10 * 86_400_000)
    ltr = LongTimeRangePlanner(
        raw, ds, lambda: earliest_raw, lambda: 1 << 62,
        persisted_planner=pers, persisted_range_fn=lambda: p_range)
    p = _plan("rate(foo[5m])", start_ms // 1000,
              (start_ms + 6 * 3600_000) // 1000)
    out = ltr.materialize(p, QueryContext())
    assert isinstance(out, StitchRvsExec)
    assert len(ds.materialized) == 1
    assert len(pers.materialized) == 1
    assert len(raw.materialized) == 1
    dsp, pp, rp = (ds.materialized[0], pers.materialized[0],
                   raw.materialized[0])
    # raw starts at the first instant whose full 5m window is in memory
    assert rp.start_ms >= earliest_raw + 300_000
    assert (rp.start_ms - p.start_ms) % p.step_ms == 0
    # persisted ends right before raw begins; ds right before persisted
    assert pp.end_ms == rp.start_ms - p.step_ms
    assert pp.start_ms >= p_range[0] + 300_000
    assert dsp.end_ms == pp.start_ms - p.step_ms
    assert dsp.start_ms == p.start_ms


def test_ltr_no_segments_falls_back_to_downsample():
    start_ms = 1_600_000_000_000
    raw, ds, pers = (_RecordingPlanner("raw"), _RecordingPlanner("ds"),
                     _RecordingPlanner("pers"))
    ltr = LongTimeRangePlanner(
        raw, ds, lambda: start_ms + 10 * 3600_000, lambda: 1 << 62,
        persisted_planner=pers, persisted_range_fn=lambda: None)
    p = _plan("rate(foo[5m])", start_ms // 1000,
              (start_ms + 3600_000) // 1000)
    ltr.materialize(p, QueryContext())
    assert len(pers.materialized) == 0
    assert len(ds.materialized) == 1


def test_ltr_fully_in_raw_never_touches_cold_tiers():
    start_ms = 1_600_000_000_000
    raw, ds, pers = (_RecordingPlanner("raw"), _RecordingPlanner("ds"),
                     _RecordingPlanner("pers"))
    ltr = LongTimeRangePlanner(
        raw, ds, lambda: start_ms - 86_400_000, lambda: 1 << 62,
        persisted_planner=pers,
        persisted_range_fn=lambda: (0, start_ms))
    p = _plan("rate(foo[5m])", start_ms // 1000,
              (start_ms + 3600_000) // 1000)
    ltr.materialize(p, QueryContext())
    assert len(raw.materialized) == 1
    assert not ds.materialized and not pers.materialized


def test_ltr_head_older_than_segments_falls_back_to_raw():
    """No downsample tier: grid instants older than segment coverage must
    route to the raw cluster's chunk-paging path, never be dropped."""
    start_ms = 1_600_000_000_000
    raw, pers = _RecordingPlanner("raw"), _RecordingPlanner("pers")
    earliest_raw = start_ms + 5 * 3600_000
    p_range = (start_ms + 2 * 3600_000, start_ms + 10 * 86_400_000)
    ltr = LongTimeRangePlanner(
        raw, None, lambda: earliest_raw, lambda: 1 << 62,
        persisted_planner=pers, persisted_range_fn=lambda: p_range)
    p = _plan("rate(foo[5m])", start_ms // 1000,
              (start_ms + 8 * 3600_000) // 1000)
    out = ltr.materialize(p, QueryContext())
    assert isinstance(out, StitchRvsExec)
    assert len(pers.materialized) == 1
    # head before segment coverage AND the in-memory tail both go to raw
    assert len(raw.materialized) == 2
    head = min(raw.materialized, key=lambda pl: pl.start_ms)
    assert head.start_ms == p.start_ms
    assert head.end_ms == pers.materialized[0].start_ms - p.step_ms


def test_retention_keeps_frames_ingested_after_last_compaction(tmp_path):
    """A backfill frame flushed AFTER the compaction pass read the index
    must survive retention until a later pass folds it into a segment."""
    ts_grid, pks, vals = _grid(), _pks(), _vals()
    cs = LocalDiskColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(column_store=cs)
    sh = ms.setup(DS, 0)
    sh.ingest_columns("gauge", pks, np.broadcast_to(ts_grid, (S, NS)),
                      {"value": vals})
    sh.flush_all_groups()
    seg_store = SegmentStore(str(tmp_path))
    comp = SegmentCompactor(cs, seg_store, DS, 1, window_ms=WINDOW,
                            closed_lag_ms=0)
    now = int(ts_grid[-1]) + 10 * WINDOW
    comp.compact_all(now_ms=now)
    # backfill lands AFTER the pass: old data timestamps, fresh ingestion
    late_pk = [PartKey("m", (("inst", "late"), ("_ws_", "w"),
                             ("_ns_", "n")))]
    sh.ingest_columns("gauge", late_pk, ts_grid[None, :5],
                      {"value": np.full((1, 5), 3.0)})
    sh.flush_all_groups()
    comp.enforce_retention(retain_raw_ms=1, now_ms=now)
    # the late frame survived (its ingestion time postdates the pass)
    assert cs.read_chunks(DS, 0, late_pk[0], int(ts_grid[0]),
                          int(ts_grid[-1]))
    # a later compact pass folds it in; only then is it prunable
    assert comp.compact_all(now_ms=now) >= 1
    comp.enforce_retention(retain_raw_ms=1, now_ms=now)
    assert cs.read_chunks(DS, 0, late_pk[0], int(ts_grid[0]),
                          int(ts_grid[-1])) == []
    metas = seg_store.list(DS, 0)
    blockful = sum(m.num_samples for m in metas)
    assert blockful == S * NS + 5        # nothing lost


def test_persisted_scan_cap_counts_matched_rows_only(tmp_path):
    """The cold scan cap must reflect the FILTERED working set (hot-leaf
    parity), not the shard's total segment volume."""
    from filodb_tpu.query.rangevector import PlannerParams
    ts_grid, pks, vals = _grid(), _pks(), _vals()
    cs = LocalDiskColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(column_store=cs)
    sh = ms.setup(DS, 0)
    sh.ingest_columns("gauge", pks, np.broadcast_to(ts_grid, (S, NS)),
                      {"value": vals})
    sh.flush_all_groups()
    seg_store = SegmentStore(str(tmp_path))
    SegmentCompactor(cs, seg_store, DS, 1, window_ms=WINDOW,
                     closed_lag_ms=0).compact_all(
        now_ms=int(ts_grid[-1]) + 10 * WINDOW)
    tier = PersistedTier(seg_store, DS, 1,
                         ColdSegmentCache(256 << 20, use_placer=False))
    mapper = _mapper()
    eng = QueryEngine(DS, _Src(ms), mapper,
                      planner=PersistedClusterPlanner(DS, mapper, tier))
    start_s = int(ts_grid[0]) // 1000 + 1800
    end_s = int(ts_grid[-1]) // 1000
    # limit sized for ONE series' samples (+ slack), far below total
    params = PlannerParams(scan_limit=NS + NS // 2, enforced_limits=True)
    res = eng.query_range('m{inst="i1"}', start_s, 300, end_s,
                          planner_params=params)
    assert res.error is None, res.error
    assert res.num_series == 1
    # the broad query over the same limit is rejected
    res = eng.query_range("m", start_s, 300, end_s, planner_params=params)
    assert res.error is not None and "scan limit" in res.error


def test_persisted_planner_splits_long_ranges():
    mapper = _mapper()

    class _FakeTier:
        plan_split_ms = 24 * 3600 * 1000
        schemas = None

        def covering(self, *a, **k):
            return []

    planner = PersistedClusterPlanner(DS, mapper, _FakeTier())
    start_s = 1_600_000_000
    p = _plan('sum(rate(m[5m]))', start_s, start_s + 5 * 86_400, step_s=300)
    out = planner.materialize(p, QueryContext())
    assert isinstance(out, StitchRvsExec)
    assert len(out.children) >= 5
    # leaves are persisted-segment execs
    leaf = out.children[0]
    while getattr(leaf, "children", None):
        leaf = leaf.children[0]
    assert isinstance(leaf, SelectPersistedSegmentsExec)
