"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-shard / multi-host sharding
logic is exercised without TPU hardware (the reference's analogue is the
multi-JVM test harness, ref: standalone/src/multi-jvm).  Environment variables
must be set before jax is imported anywhere.
"""
import os

# Force CPU: the ambient environment points JAX at the real TPU (platform
# 'axon'); unit tests must not occupy it and need 8 virtual devices.  The
# TPU plugin is registered by a sitecustomize hook at interpreter start, so
# jax is already imported — env vars alone are too late; use jax.config.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Float64 on CPU for exact-semantics conformance tests against the reference's
# double-precision math; the TPU runtime path uses float32 (see filodb_tpu.config).
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def mesh8():
    from jax.sharding import Mesh
    devs = np.array(jax.devices("cpu")[:8]).reshape(8)
    return Mesh(devs, ("shard",))


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tests (run by "
        "default; deselect with -m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: fault-injection / node-kill chaos tests "
        "(subprocess clusters, SIGKILL, wall-clock waits). Implies slow, "
        "so tier-1's -m 'not slow' excludes them; run explicitly with "
        "-m chaos or via `python bench.py chaos`.")
    config.addinivalue_line(
        "markers", "multichip: multi-device equivalence tests (per-device "
        "fused dispatch, sharded DeviceMirror, partial merges). Auto-skip "
        "below 2 local devices so tier-1 stays green on 1-device boxes; "
        "this harness forces 8 virtual CPU devices, so they normally run.")
    config.addinivalue_line(
        "markers", "replication: chaos-style replication tests (multi-"
        "store clusters under live ingest+query traffic, handoff drills, "
        "wall-clock waits). Implies slow, so tier-1's -m 'not slow' "
        "excludes them; run explicitly with -m replication or via "
        "`python bench.py replication`.")


def pytest_collection_modifyitems(config, items):
    # chaos implies slow: the tier-1 gate (-m 'not slow') must never pay
    # for subprocess spawn + SIGKILL + restart cycles
    few_devices = jax.local_device_count() < 2
    skip_multichip = pytest.mark.skip(
        reason="multichip tests need >= 2 local devices "
               "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    for item in items:
        if "chaos" in item.keywords and "slow" not in item.keywords:
            item.add_marker(pytest.mark.slow)
        if "replication" in item.keywords and "slow" not in item.keywords:
            item.add_marker(pytest.mark.slow)
        if few_devices and "multichip" in item.keywords:
            item.add_marker(skip_multichip)
