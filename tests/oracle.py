"""Brute-force numpy oracle for PromQL window semantics.

Implements the reference behavior sample-by-sample (window = samples with
ts in [wend-range+1, wend]; extrapolation per RateFunctions.scala:37-76;
counter correction by walking resets) so kernel tests compare the vectorized
TPU implementations against an independently-written scalar model.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np


def correct_counter(vals: Sequence[float]) -> List[float]:
    out = []
    corr = 0.0
    prev = None
    for v in vals:
        if v is None or (isinstance(v, float) and math.isnan(v)):
            out.append(float("nan"))
            continue
        if prev is not None and v < prev:
            # full previous value: the counter restarted from zero
            # (ref: DoubleVector.scala:328 `_correction += last`)
            corr += prev
        prev = v
        out.append(v + corr)
    return out


def extrapolated_rate(window_start: float, window_end: float, n: int,
                      t1: float, v1: float, t2: float, v2: float,
                      is_counter: bool, is_rate: bool) -> float:
    if n < 2:
        return float("nan")
    dur_start = (t1 - window_start) / 1000.0
    dur_end = (window_end - t2) / 1000.0
    sampled = (t2 - t1) / 1000.0
    avg = sampled / (n - 1)
    delta = v2 - v1
    if is_counter and delta > 0 and v1 >= 0:
        dur_zero = sampled * (v1 / delta)
        if dur_zero < dur_start:
            dur_start = dur_zero
    threshold = avg * 1.1
    extrap = sampled
    extrap += dur_start if dur_start < threshold else avg / 2
    extrap += dur_end if dur_end < threshold else avg / 2
    scaled = delta * (extrap / sampled)
    if is_rate:
        return scaled / (window_end - window_start) * 1000.0
    return scaled


def window_samples(ts: np.ndarray, vals: np.ndarray, wend: int, range_ms: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    lo = wend - range_ms + 1
    m = (ts >= lo) & (ts <= wend)
    return ts[m], vals[m]


def eval_series(ts: np.ndarray, vals: np.ndarray, wends: Sequence[int],
                range_ms: int, fn: str, params: Tuple = ()) -> np.ndarray:
    """Evaluate one range function over one series, one value per window."""
    out = np.full(len(wends), np.nan)
    corrected = np.array(correct_counter(list(vals)))
    for i, wend in enumerate(wends):
        wt, wv = window_samples(ts, vals, wend, range_ms)
        mask = ~np.isnan(wv)
        if fn in ("rate", "increase", "irate"):
            _, wc = window_samples(ts, corrected, wend, range_ms)
        if len(wt) == 0:
            if fn == "absent_over_time":
                out[i] = 1.0
            continue
        if fn == "rate" or fn == "increase":
            # NaN slots are ABSENT samples (staleness markers): upstream
            # filters them out of range vectors before rate math, so the
            # boundaries are the first/last VALID samples and n counts
            # valid samples only (Prometheus extrapolatedRate contract)
            if mask.sum() >= 2:
                vt, vc = wt[mask], wc[mask]
                out[i] = extrapolated_rate(wend - range_ms, wend,
                                           int(mask.sum()),
                                           vt[0], vc[0], vt[-1], vc[-1],
                                           True, fn == "rate")
        elif fn == "delta":
            if mask.sum() >= 2:
                vt, vd = wt[mask], wv[mask]
                out[i] = extrapolated_rate(wend - range_ms, wend,
                                           int(mask.sum()),
                                           vt[0], vd[0], vt[-1], vd[-1],
                                           False, False)
        elif fn == "irate":
            if mask.sum() >= 2:
                vt, vc = wt[mask], wc[mask]
                out[i] = (vc[-1] - vc[-2]) / ((vt[-1] - vt[-2]) / 1000.0)
        elif fn == "idelta":
            if mask.sum() >= 2:
                vd = wv[mask]
                out[i] = vd[-1] - vd[-2]
        elif fn == "sum_over_time":
            # all-NaN windows are absent: the reference accumulator starts
            # at NaN and only zeroes on the first non-NaN chunk (ref:
            # AggrOverTimeFunctions.scala:153-165)
            out[i] = np.sum(wv[mask]) if mask.any() else np.nan
        elif fn == "count_over_time":
            out[i] = np.sum(mask)
        elif fn == "avg_over_time":
            out[i] = np.mean(wv[mask]) if mask.any() else np.nan
        elif fn == "min_over_time":
            out[i] = np.min(wv[mask]) if mask.any() else np.nan
        elif fn == "max_over_time":
            out[i] = np.max(wv[mask]) if mask.any() else np.nan
        elif fn == "stddev_over_time":
            out[i] = np.std(wv[mask]) if mask.any() else np.nan
        elif fn == "stdvar_over_time":
            out[i] = np.var(wv[mask]) if mask.any() else np.nan
        elif fn == "last_over_time":
            out[i] = wv[-1]
        elif fn == "mad_over_time":
            if mask.any():
                xs = wv[mask]
                med = np.quantile(xs, 0.5, method="linear")
                out[i] = np.quantile(np.abs(xs - med), 0.5, method="linear")
        elif fn == "quantile_over_time":
            q = params[0]
            out[i] = (np.quantile(wv[mask], q, method="linear")
                      if mask.any() else np.nan)
        elif fn == "changes":
            # pairs of consecutive valid samples fully inside window
            prev = None
            cnt = 0
            # find index of first window sample in the full series
            for t, v in zip(ts, vals):
                if t < wend - range_ms + 1 or t > wend or np.isnan(v):
                    continue
                if prev is not None and v != prev:
                    cnt += 1
                prev = v
            out[i] = cnt
        elif fn == "resets":
            prev = None
            cnt = 0
            for t, v in zip(ts, vals):
                if t < wend - range_ms + 1 or t > wend or np.isnan(v):
                    continue
                if prev is not None and v < prev:
                    cnt += 1
                prev = v
            out[i] = cnt
        elif fn == "deriv":
            if mask.sum() >= 2:
                t_s = wt[mask] / 1000.0
                slope, _ = np.polyfit(t_s, wv[mask], 1)
                out[i] = slope
        elif fn == "predict_linear":
            if mask.sum() >= 2:
                t_s = wt[mask] / 1000.0
                slope, icept = np.polyfit(t_s, wv[mask], 1)
                out[i] = slope * (wend / 1000.0 + params[0]) + icept
        elif fn == "z_score":
            if mask.any():
                mean = np.mean(wv[mask])
                std = np.std(wv[mask])
                out[i] = (wv[-1] - mean) / std
        elif fn == "holt_winters":
            sf, tf = params
            xs = wv[mask]
            if len(xs) >= 2:
                s_prev = xs[0]
                b = xs[1] - xs[0]
                for j in range(1, len(xs)):
                    if j > 1:
                        b = tf * (s_prev - s_prev2) + (1 - tf) * b
                    s_prev2, s_prev = s_prev, sf * xs[j] + (1 - sf) * (s_prev + b)
                out[i] = s_prev
        elif fn == "timestamp":
            # the last VALID sample's time — NaN slots are absent samples
            # under the FiloDB convention, so they carry no timestamp
            out[i] = wt[mask][-1] / 1000.0 if mask.any() else float("nan")
        elif fn == "present_over_time":
            out[i] = 1.0
        elif fn == "absent_over_time":
            pass
        else:
            raise ValueError(fn)
    return out
