"""f32 conformance: the production TPU numeric path validated vs the f64 oracle.

The TPU runtime computes in float32 (filodb_tpu.config.compute_dtype); the
reference computes everything in f64 where cancellation is benign (ref:
query/.../rangefn/RateFunctions.scala, AggrOverTimeFunctions.scala).  These
tests run the kernels exactly as the leaf exec feeds them on chip — f64
host-side counter correction (ops/counter.host_counter_correct), per-series
value rebasing (ops/timewindow.series_value_base), then an f32 downcast —
and compare against tests/oracle.py in f64, parameterized over counter
magnitudes up to 2^40 (far past the 2^24 limit where absolute f32 loses
every per-sample delta).

f32-on-CPU is bit-for-bit IEEE-754 binary32, the same numeric model the TPU
VPU uses for these elementwise/scan ops, so this certifies the production
dtype without needing the (tunneled, flaky) chip in CI; bench.py exercises
the same kernels on the real device.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from filodb_tpu.ops.counter import host_counter_correct
from filodb_tpu.utils.jaxcompat import enable_x64
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import (make_window_ends, series_value_base,
                                       to_offsets)

from oracle import eval_series

STEP_MS = 10_000
T = 240
RANGE_MS = 300_000
BASES = [0.0, 2.0**24, 1.0e9, 2.0**31, 2.0**40]


def _mk_data(base, S=6, with_resets=False, with_gaps=True, seed=11):
    """Counter-ish series at absolute magnitude `base`; f64 ground truth."""
    rng = np.random.default_rng(seed)
    ts = np.arange(T, dtype=np.int64) * STEP_MS
    inc = rng.exponential(10.0, size=(S, T))
    vals = base + np.cumsum(inc, axis=1)
    if with_resets:
        # process restart: counter restarts near zero (NOT near base) — the
        # hostile case where the drop magnitude exceeds f32 resolution
        for s in range(S):
            r = int(rng.integers(T // 3, 2 * T // 3))
            vals[s, r:] = np.cumsum(inc[s, r:])
    if with_gaps:
        gap = rng.random((S, T)) < 0.05
        vals[gap] = np.nan
    return ts, vals


def _run_kernel_f32(ts, vals_abs, wends, fn, params=()):
    """The leaf-exec device path in f32: f64 correct (counter fns) ->
    f64 rebase -> f32 downcast -> kernel with vbase."""
    S = vals_abs.shape[0]
    spec = RANGE_FUNCTIONS[fn]
    v64 = vals_abs.astype(np.float64)
    if spec.is_counter:
        v64 = host_counter_correct(v64)
    vbase = series_value_base(v64)
    rebased = (v64 - vbase[:, None]).astype(np.float32)
    counts = np.full(S, T)
    ts_off = to_offsets(np.tile(ts, (S, 1)), counts, 0)
    with enable_x64(False):
        out = evaluate_range_function(
            jnp.asarray(ts_off), jnp.asarray(rebased),
            jnp.asarray(wends.astype(np.int32)), RANGE_MS, fn,
            tuple(params), vbase=jnp.asarray(vbase.astype(np.float32)),
            dense=not bool(np.isnan(vals_abs).any()))
        return np.asarray(out)


def _oracle(ts, vals_abs, wends, fn, params=()):
    return np.stack([eval_series(ts, vals_abs[s], wends, RANGE_MS, fn, params)
                     for s in range(vals_abs.shape[0])])


WENDS = make_window_ends(400_000, (T - 1) * STEP_MS, 60_000)

COUNTER_FNS = ["rate", "increase", "irate"]
# shift-invariant: computed on rebased (small) values, exact at any base
SHIFT_INVARIANT_FNS = ["stddev_over_time", "deriv",
                       "z_score", "count_over_time", "idelta", "delta",
                       "changes", "resets"]
# absolute-output: base re-added in f32 -> relative accuracy ~f32 eps
ABSOLUTE_FNS = ["sum_over_time", "avg_over_time", "min_over_time",
                "max_over_time", "last_over_time"]


def _compare(got, want, rtol, atol=1e-6):
    assert got.shape == want.shape
    nan_g, nan_w = np.isnan(got), np.isnan(want)
    assert (nan_g == nan_w).all(), "NaN placement differs from oracle"
    m = ~nan_w
    np.testing.assert_allclose(got[m], want[m], rtol=rtol, atol=atol)


@pytest.mark.parametrize("base", BASES)
@pytest.mark.parametrize("fn", COUNTER_FNS)
def test_counter_fns_f32_with_resets(base, fn):
    """rate/increase/irate in f32 at counter magnitudes up to 2^40,
    including resets — the VERDICT round-1 'likely wrong' case."""
    ts, vals = _mk_data(base, with_resets=True)
    got = _run_kernel_f32(ts, vals, WENDS, fn)
    want = _oracle(ts, vals, WENDS, fn)
    # deltas are exact post-correction; remaining error is f32 arithmetic in
    # the extrapolation formula
    _compare(got, want, rtol=2e-5, atol=1e-4)


@pytest.mark.parametrize("base", BASES)
@pytest.mark.parametrize("fn", SHIFT_INVARIANT_FNS)
def test_shift_invariant_fns_f32(base, fn):
    ts, vals = _mk_data(base, with_resets=False)
    got = _run_kernel_f32(ts, vals, WENDS, fn)
    want = _oracle(ts, vals, WENDS, fn)
    # stddev/z_score involve sqrt of differences of f32 sums over windows of
    # magnitude ~1e3 rebased values; allow looser but still tight bounds
    _compare(got, want, rtol=5e-4, atol=5e-3)


@pytest.mark.parametrize("base", BASES)
def test_stdvar_f32(base):
    """Variance without sqrt keeps the full cumsum-cancellation noise of the
    s2/c - mean^2 trick in f32 (~1-2% worst case at these magnitudes) —
    documented tolerance, tighter after sqrt (see stddev above)."""
    ts, vals = _mk_data(base, with_resets=False)
    got = _run_kernel_f32(ts, vals, WENDS, "stdvar_over_time")
    want = _oracle(ts, vals, WENDS, "stdvar_over_time")
    _compare(got, want, rtol=2e-2, atol=5e-3)


@pytest.mark.parametrize("base", BASES)
@pytest.mark.parametrize("fn", ABSOLUTE_FNS)
def test_absolute_fns_f32(base, fn):
    ts, vals = _mk_data(base, with_resets=False)
    got = _run_kernel_f32(ts, vals, WENDS, fn)
    want = _oracle(ts, vals, WENDS, fn)
    # output magnitude ~= base; f32 can only promise ~1e-7 relative, and the
    # cumsum window trick loses a few more bits at 2^40
    _compare(got, want, rtol=3e-6, atol=1e-3)


def test_naive_f32_rate_is_wrong_at_2_30():
    """Documents WHY the rebasing path exists: casting absolute counters to
    f32 destroys rate at >= 2^24 magnitudes (round-1 VERDICT Weak #3)."""
    ts, vals = _mk_data(2.0**30, with_resets=False, with_gaps=False)
    S = vals.shape[0]
    counts = np.full(S, T)
    ts_off = to_offsets(np.tile(ts, (S, 1)), counts, 0)
    with enable_x64(False):
        naive = np.asarray(evaluate_range_function(
            jnp.asarray(ts_off), jnp.asarray(vals.astype(np.float32)),
            jnp.asarray(WENDS.astype(np.int32)), RANGE_MS, "rate"))
    want = _oracle(ts, vals, WENDS, "rate")
    m = ~np.isnan(want)
    rel_err = np.abs(naive[m] - want[m]) / np.abs(want[m])
    assert np.median(rel_err) > 0.01, (
        "naive f32 unexpectedly accurate — rebasing may be redundant now")
    # and the production path is NOT wrong on the same data
    got = _run_kernel_f32(ts, vals, WENDS, "rate")
    _compare(got, want, rtol=2e-5, atol=1e-4)


def test_end_to_end_sum_rate_f32_large_counters():
    """Full engine path (ingest -> leaf exec -> PSM -> aggregate) in f32 with
    counters at 1e9: exercises the host-correct + rebase + mirror plumbing,
    not just the kernel."""
    from test_query_engine import _mk_engine, START_MS
    from filodb_tpu.ingest.generator import counter_batch

    batch = counter_batch(20, T, start_ms=START_MS)
    base_offsets = 1.0e9 + np.arange(20) * 1e7
    # lift every series to its own large absolute magnitude
    batch.columns["count"] += base_offsets[batch.part_idx]
    engine = _mk_engine([batch])

    start_s = START_MS // 1000 + 600
    end_s = START_MS // 1000 + (T - 1) * 10
    with enable_x64(False):
        res = engine.query_range('sum(rate(request_total[5m]))',
                                 start_s, 60, end_s)
    assert res.error is None
    assert res.num_series == 1
    got = np.asarray(res.blocks[0].values[0])

    # oracle: per-series f64 rate, summed
    ts_abs = START_MS + np.arange(T, dtype=np.int64) * STEP_MS
    vals = batch.columns["count"].reshape(20, T)
    wends = make_window_ends(start_s * 1000, end_s * 1000, 60_000)
    want = np.sum(np.stack([
        eval_series(ts_abs, vals[s], wends, RANGE_MS, "rate")
        for s in range(20)]), axis=0)
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=1e-4)


@pytest.mark.parametrize("base", BASES)
@pytest.mark.parametrize("fn", ["rate", "increase", "sum_over_time",
                                "avg_over_time"])
def test_fused_kernel_f32_vs_oracle(base, fn):
    """The Pallas fused kernel (interpret mode, f32 inputs end to end) vs
    the f64 oracle, group-summed — parameterized over counter magnitudes
    up to 2^40.  Dense data (no gaps): the fused path's eligibility gate
    requires a fully-finite shared grid."""
    from filodb_tpu.ops.counter import rebase_values
    from filodb_tpu.ops.pallas_fused import (build_plan,
                                             fused_rate_groupsum,
                                             present_sum)
    ts, vals = _mk_data(base, S=6, with_resets=(fn in ("rate", "increase")),
                        with_gaps=False)
    G = 2
    gids = (np.arange(vals.shape[0]) % G).astype(np.int32)
    plan = build_plan(ts, WENDS, RANGE_MS)
    is_counter = fn in ("rate", "increase")
    reb, vbase = rebase_values(vals, is_counter)
    with enable_x64(False):
        sums, counts = fused_rate_groupsum(
            reb.astype(np.float32), vbase.astype(np.float32), gids, plan,
            G, fn_name=fn, precorrected=is_counter, interpret=True)
        got = present_sum(sums, counts)
    per = _oracle(ts, vals, WENDS, fn)
    want = np.zeros((G, len(WENDS)))
    cnt = np.zeros((G, len(WENDS)))
    for s in range(vals.shape[0]):
        ok = ~np.isnan(per[s])
        want[gids[s]][ok] += per[s][ok]
        cnt[gids[s]][ok] += 1
    want = np.where(cnt > 0, want, np.nan)
    # documented f32 error envelope: deltas exact via rebasing; absolute
    # *_over_time sums inherit base/|window sum| relative rounding
    rtol = 2e-4 if fn in ("rate", "increase") else 2e-3
    atol = 1e-3 if fn in ("rate", "increase") else base * 2e-6 + 1e-3
    _compare(got, want, rtol=rtol, atol=atol)
